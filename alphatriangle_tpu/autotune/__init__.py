"""Fit-driven autotuner: offline config search that spends HBM, not
chip windows (docs/AUTOTUNE.md).

Searches the `(SELF_PLAY_BATCH_SIZE, BUFFER_CAPACITY, chunk T, fused
K, dp, geometry preset)` space with `estimate_fit`/`compose_budget`
(telemetry/memory.py) as the feasibility oracle — candidates are
AOT-analyzed, never executed — and an analytic throughput model
(utils/flops.py + device peak, calibrated against ledger history) as
the objective. `cli tune` drives it and emits `tuned_preset.json`
artifacts that `cli train --preset`, `cli warm`, `cli fit` and
`bench.py` consume directly."""

from .artifact import (
    TUNE_OUTCOME_KIND,
    build_tuned_preset,
    default_artifact_path,
    ledger_tune_outcome,
    write_tuned_preset,
)
from .model import (
    Calibration,
    calibration_from_summary,
    calibration_from_targets,
    default_moves_per_game,
    expected_simulations,
    merge_calibrations,
    predict_throughput,
)
from .search import (
    TuneResult,
    default_oracle,
    materialize_candidate,
    ring_bytes_for,
    run_search,
)
from .space import (
    STATUS_DOMINATED,
    STATUS_FIT,
    STATUS_GATE,
    STATUS_OVER,
    STATUS_RING,
    Candidate,
    SearchSpace,
    divisibility_gate,
    prune_dominated,
)

__all__ = [
    "Calibration",
    "Candidate",
    "STATUS_DOMINATED",
    "STATUS_FIT",
    "STATUS_GATE",
    "STATUS_OVER",
    "STATUS_RING",
    "SearchSpace",
    "TUNE_OUTCOME_KIND",
    "TuneResult",
    "build_tuned_preset",
    "calibration_from_summary",
    "calibration_from_targets",
    "default_artifact_path",
    "default_moves_per_game",
    "default_oracle",
    "divisibility_gate",
    "expected_simulations",
    "ledger_tune_outcome",
    "materialize_candidate",
    "merge_calibrations",
    "predict_throughput",
    "prune_dominated",
    "ring_bytes_for",
    "run_search",
    "write_tuned_preset",
]
