"""Tuned-preset artifact + prediction-vs-observed outcome ledgering.

`cli tune` emits `runs/<run>/tuned_preset.json`
(`config.presets.TUNED_PRESET_SCHEMA`): the winning candidate's full
config bundle plus the prediction, composed budget, calibration
provenance and the search table. `config.presets.load_tuned_preset`
round-trips it into a `baseline_preset`-shaped bundle that
`cli train --preset <path>`, `cli warm <path>`, `cli fit <path>` and
`bench.py` (BENCH_TUNED_PRESET) consume directly.

After a run that consumed a tuned preset completes,
`ledger_tune_outcome` appends a `kind:"tune_outcome"` record to the
run's metrics ledger: predicted vs observed games/h and moves/s and
their ratio. `calibration_from_targets` (autotune/model.py) folds those
ratios back into the next search's efficiency term — the closed
calibration loop the ISSUE names: each completed run sharpens the next
search."""

import json
import logging
import time
from pathlib import Path

from ..config.presets import TUNED_PRESET_SCHEMA

logger = logging.getLogger(__name__)

TUNE_OUTCOME_KIND = "tune_outcome"


def build_tuned_preset(
    result,
    env_config,
    model_config,
    mcts_config,
    train_config,
    scale: str,
    mode: str,
    backend: str,
    device_kind: str,
    limit_bytes,
    limit_source: str,
    calibration,
    run_name: str,
) -> dict:
    """The `tuned_preset.json` payload for a completed search with a
    winner. `result` is the TuneResult; the configs are the WINNING
    candidate's materialized configs (not the base plan's)."""
    cand = result.best
    if cand is None:
        raise ValueError("build_tuned_preset needs a feasible winner")
    return {
        "schema": TUNED_PRESET_SCHEMA,
        "created": time.time(),
        "run_name": run_name,
        "description": (
            f"autotuned {scale} ({mode}) on {backend}"
            f"{f'/{device_kind}' if device_kind else ''}: "
            f"{cand.label()}"
        ),
        "scale": scale,
        "mode": mode,
        "backend": backend,
        "device_kind": device_kind,
        "candidate": {
            "geometry": cand.geometry,
            "sp_batch": cand.sp_batch,
            "capacity": cand.capacity,
            "chunk": cand.chunk,
            "fused_k": cand.fused_k,
            "dp": cand.dp,
        },
        # Kernel-axis provenance (docs/KERNELS.md): which lowering of
        # each hot kernel and which rollout inference precision the
        # winner was scored with. The same values are threaded into
        # the config bundle below, so `--preset` runs reproduce them;
        # this block keeps them auditable without config spelunking.
        "kernels": cand.kernels(),
        "configs": {
            "env": env_config.model_dump(),
            "model": model_config.model_dump(),
            "mcts": mcts_config.model_dump(),
            "train": train_config.model_dump(),
        },
        "predicted": result.best_prediction,
        "budget": result.best_budget,
        "limit_bytes": limit_bytes,
        "limit_source": limit_source,
        "calibration": (
            calibration.as_dict() if calibration is not None else None
        ),
        "search": {
            "rows": result.rows,
            "oracle_calls": result.oracle_calls,
            "evaluated": result.evaluated,
        },
    }


def write_tuned_preset(payload: dict, out_path) -> Path:
    """Write the artifact (parents created); returns the path."""
    path = Path(out_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2, default=str))
    return path


def default_artifact_path(run_name: str, root_dir=None) -> Path:
    """`runs/<run_name>/tuned_preset.json` under the runs root (the
    same resolution `cli perf`/`cli mem` use for run names)."""
    from ..config.persistence_config import PersistenceConfig

    persistence = PersistenceConfig(RUN_NAME=run_name)
    if root_dir:
        persistence = persistence.model_copy(
            update={"ROOT_DATA_DIR": str(root_dir)}
        )
    return persistence.get_run_base_dir() / "tuned_preset.json"


def ledger_tune_outcome(run_dir, tuned_payload: dict) -> "dict | None":
    """Append predicted-vs-observed throughput to a completed run's
    metrics ledger.

    Reads the run's util records (tolerantly — telemetry/perf.py),
    aligns observed games/h and moves/s against the tuned preset's
    prediction, and appends one `kind:"tune_outcome"` JSON line to the
    run's metrics.jsonl. Returns the record, or None when the run has
    no ledger at all (nothing to anchor the observation to). A run too
    short to produce util records still gets a record with null
    observed fields — the prediction provenance is worth keeping."""
    from ..telemetry.ledger import read_ledger, resolve_ledger_path
    from ..telemetry.perf import summarize_utilization

    run_dir = Path(run_dir)
    ledger = resolve_ledger_path(run_dir)
    if ledger is None:
        logger.warning(
            "tune: no metrics ledger under %s; outcome not recorded",
            run_dir,
        )
        return None
    summary = summarize_utilization(read_ledger(ledger)) or {}
    predicted = tuned_payload.get("predicted") or {}
    record: dict = {
        "kind": TUNE_OUTCOME_KIND,
        "time": time.time(),
        "tuned_run_name": tuned_payload.get("run_name"),
        "schema": tuned_payload.get("schema"),
        "candidate": tuned_payload.get("candidate"),
        "predicted_games_per_hour": predicted.get("games_per_hour"),
        "predicted_moves_per_sec": predicted.get("moves_per_sec"),
        "observed_games_per_hour": summary.get("games_per_hour"),
        "observed_moves_per_sec": summary.get("moves_per_sec"),
        "observed_mfu": summary.get("mfu"),
    }
    pred = record["predicted_games_per_hour"]
    obs = record["observed_games_per_hour"]
    if (
        isinstance(pred, (int, float))
        and isinstance(obs, (int, float))
        and pred > 0
        and obs > 0
    ):
        record["observed_over_predicted"] = obs / pred
    with ledger.open("a") as fh:
        fh.write(json.dumps(record) + "\n")
    logger.info(
        "tune: outcome ledgered to %s (predicted %.1f games/h, "
        "observed %s)",
        ledger,
        pred if isinstance(pred, (int, float)) else float("nan"),
        f"{obs:.1f}" if isinstance(obs, (int, float)) else "n/a",
    )
    return record
