"""Analytic throughput model + ledger calibration for the autotuner.

The objective the search maximizes is PREDICTED games/hour, composed
from first principles so it never needs to execute a candidate:

- per-lane-move model FLOPs: one network forward per MCTS simulation
  leaf (+ ~one root eval per move), with playout-cap randomization
  folding `fast_simulations`/`full_search_prob` into an expected sim
  count, plus the learner's amortized share (each experience is
  consumed once at replay ratio 1: `train_step_flops / BATCH_SIZE`).
  FLOPs come from `utils/flops.py` — the same accounting the live
  `UtilizationMeter` uses, so predictions and observations share a
  currency.
- compute time: FLOPs / (efficiency x peak bf16 FLOP/s x dp). The
  efficiency term is WHERE calibration enters: it is the achieved MFU
  of prior comparable runs (ledger history via
  `telemetry.perf.load_comparable`), falling back to a documented
  default when no history exists.
- dispatch overhead: a per-host-launch constant amortized over the
  rollout chunk T; the fused megastep collapses a sync iteration's
  ~`2 + ceil(B*T/(lbatch*K))` launches to 1, which is exactly why T, K
  and the loop mode appear in the search space at all.

The model is deliberately monotone non-decreasing in B, T and K (the
dominance prune in autotune/space.py relies on monotone-in-B), and
BUFFER_CAPACITY does not appear: ring size costs memory, not time, so
the search spends whatever HBM the feasibility oracle says is left on
capacity — "spend HBM, not chip windows".

Nothing here imports JAX; predictions run beside a wedged chip.
"""

import logging
import math
from dataclasses import dataclass, field

from ..utils.flops import forward_flops, train_step_flops

logger = logging.getLogger(__name__)

# Achieved-MFU prior when no ledger history exists: the flagship bench
# measured ~1.4% self-play MFU at B=512 (bench_config.py notes), so an
# uncalibrated search assumes roughly that. Any comparable run in the
# ledger replaces it.
DEFAULT_EFFICIENCY = 0.014

# Host-side cost of one program dispatch (seconds): queueing + transfer
# + Python driver turnaround. Conservative for a local chip, an order
# low for a tunneled dev VM; calibration cannot observe it directly, so
# it stays a documented constant rather than a fitted one.
DEFAULT_DISPATCH_OVERHEAD_S = 0.01

# Peak to assume when the device kind is unknown AND no override/
# history pins one. Only used to rank candidates against each other —
# relative ranking is insensitive to the absolute peak because every
# candidate shares the denominator.
FALLBACK_PEAK_TFLOPS = 1.0


@dataclass
class Calibration:
    """Throughput-model terms learned from ledger history.

    `efficiency` is achieved MFU; `moves_per_game` converts moves/s to
    games/h; `outcome_scale` multiplies predictions by the observed/
    predicted ratio of past tuned runs (`kind:"tune_outcome"` records),
    so every completed run sharpens the next search. `family_seconds`
    is measured p50 dispatch wall per program family (rollout /
    learner / megastep / serve) from the run's flight ring
    (telemetry/flight.py) — ground truth the analytic FLOP model can
    be sanity-checked against. `cost_flops` is compiler-reported FLOPs
    per dispatch per family (XLA `cost_analysis()` records captured by
    the roofline plane, telemetry/roofline.py) — when present it
    anchors `efficiency` to compiler ground truth instead of the
    analytic estimate. `sources` records where each term came from for
    the artifact's provenance block.
    """

    efficiency: float = DEFAULT_EFFICIENCY
    moves_per_game: "float | None" = None
    overhead_s: float = DEFAULT_DISPATCH_OVERHEAD_S
    outcome_scale: float = 1.0
    family_seconds: dict = field(default_factory=dict)
    cost_flops: dict = field(default_factory=dict)
    sources: list = field(default_factory=lambda: ["defaults"])

    def as_dict(self) -> dict:
        return {
            "efficiency": self.efficiency,
            "moves_per_game": self.moves_per_game,
            "overhead_s_per_dispatch": self.overhead_s,
            "outcome_scale": self.outcome_scale,
            "family_seconds": dict(self.family_seconds),
            "cost_flops": dict(self.cost_flops),
            "sources": list(self.sources),
        }


def default_moves_per_game(env_config) -> float:
    """Crude geometry prior for episode length: one move places ~an
    average shape (~(MIN+MAX)/2 triangles) and a game ends when the
    playable area stops absorbing shapes — roughly playable_cells /
    avg_shape_size moves. The CPU smoke reference (3x4 board, shapes
    up to 3 triangles) measures ~4.1 moves/game against this prior's
    4.0; calibration overrides it whenever history exists."""
    playable = sum(
        hi - lo for lo, hi in env_config.PLAYABLE_RANGE_PER_ROW
    )
    avg_shape = max(
        1.0,
        (env_config.MIN_SHAPE_TRIANGLES + env_config.MAX_SHAPE_TRIANGLES)
        / 2.0,
    )
    return max(2.0, playable / avg_shape)


def expected_simulations(mcts_config) -> float:
    """Expected simulations per move under playout cap randomization
    (full searches with prob p, fast ones otherwise)."""
    full = float(mcts_config.max_simulations)
    fast = getattr(mcts_config, "fast_simulations", None)
    if not fast:
        return full
    p = float(getattr(mcts_config, "full_search_prob", 0.25) or 0.25)
    return p * full + (1.0 - p) * float(fast)


def calibration_from_summary(summary: dict) -> "Calibration | None":
    """Calibration terms from one comparable perf summary (a run ledger
    or bench snapshot normalized by `load_comparable`). None when the
    summary carries nothing usable."""
    if not isinstance(summary, dict):
        return None
    terms: dict = {}
    mfu = summary.get("mfu")
    if isinstance(mfu, (int, float)) and 0 < mfu <= 1:
        terms["efficiency"] = float(mfu)
    moves_s = summary.get("moves_per_sec")
    games_h = summary.get("games_per_hour")
    if (
        isinstance(moves_s, (int, float))
        and isinstance(games_h, (int, float))
        and moves_s > 0
        and games_h > 0
    ):
        terms["moves_per_game"] = moves_s * 3600.0 / games_h
    if not terms:
        return None
    return Calibration(
        efficiency=terms.get("efficiency", DEFAULT_EFFICIENCY),
        moves_per_game=terms.get("moves_per_game"),
        sources=[str(summary.get("source", "summary"))],
    )


def merge_calibrations(calibrations: list) -> Calibration:
    """Fold per-source calibrations into one (arithmetic mean per term;
    later runs carry no more weight than earlier ones — history is
    assumed comparable, not time-decaying)."""
    cals = [c for c in calibrations if isinstance(c, Calibration)]
    if not cals:
        return Calibration()
    effs = [c.efficiency for c in cals]
    mpgs = [
        c.moves_per_game
        for c in cals
        if isinstance(c.moves_per_game, (int, float))
    ]
    scales = [c.outcome_scale for c in cals]
    sources: list = []
    fam_samples: dict = {}
    cost_samples: dict = {}
    for c in cals:
        sources.extend(c.sources)
        for fam, secs in (c.family_seconds or {}).items():
            if isinstance(secs, (int, float)):
                fam_samples.setdefault(fam, []).append(float(secs))
        for fam, flops in (c.cost_flops or {}).items():
            if isinstance(flops, (int, float)):
                cost_samples.setdefault(fam, []).append(float(flops))
    return Calibration(
        efficiency=sum(effs) / len(effs),
        moves_per_game=(sum(mpgs) / len(mpgs)) if mpgs else None,
        overhead_s=cals[0].overhead_s,
        outcome_scale=sum(scales) / len(scales),
        family_seconds={
            fam: sum(v) / len(v) for fam, v in fam_samples.items()
        },
        cost_flops={
            fam: sum(v) / len(v) for fam, v in cost_samples.items()
        },
        sources=sources,
    )


def cost_anchored_efficiency(
    cost_flops: dict, family_seconds: dict, peak_tflops
) -> "float | None":
    """Achieved MFU implied by compiler ground truth: max over families
    of (cost_analysis FLOPs per dispatch / measured p50 dispatch wall)
    / peak FLOP/s. The max (not mean) because the model's efficiency
    term bounds what a well-shaped candidate can reach, and the busiest
    family is the one the search is shaping. None unless some family
    carries both terms and the implied fraction is sane (0 < eff <= 1
    — a torn sidecar or clock skew must not poison the search)."""
    if not isinstance(peak_tflops, (int, float)) or peak_tflops <= 0:
        return None
    best = None
    for fam, flops in (cost_flops or {}).items():
        secs = (family_seconds or {}).get(fam)
        if (
            isinstance(flops, (int, float))
            and flops > 0
            and isinstance(secs, (int, float))
            and secs > 0
        ):
            eff = (flops / secs) / (peak_tflops * 1e12)
            if 0 < eff <= 1 and (best is None or eff > best):
                best = eff
    return best


def calibration_from_targets(
    targets: list, root_dir: "str | None" = None
) -> Calibration:
    """Calibration from ledger history: each target goes through
    `load_comparable` (run name / run dir / metrics.jsonl / perf or
    bench JSON), then any `tune_outcome` records in resolvable run
    ledgers fold in as an observed/predicted scale. Unreadable targets
    are skipped with a log line, never fatal — an empty history just
    means defaults."""
    from ..telemetry.ledger import read_ledger, resolve_ledger_path
    from ..telemetry.perf import load_comparable

    cals = []
    for target in targets or []:
        summary, label = load_comparable(str(target), root_dir=root_dir)
        if summary is None:
            logger.info("tune: calibration target skipped (%s)", label)
            continue
        cal = calibration_from_summary(summary)
        if cal is None:
            logger.info(
                "tune: %s has no usable mfu/throughput fields", label
            )
            continue
        # Prediction-vs-observed feedback: tune_outcome records written
        # by `cli train --preset <tuned>` after the run completed.
        source = summary.get("source")
        ratios = []
        if source:
            from pathlib import Path

            ledger = resolve_ledger_path(Path(str(source)))
            if ledger is not None:
                for rec in read_ledger(ledger, kinds={"tune_outcome"}):
                    ratio = rec.get("observed_over_predicted")
                    if isinstance(ratio, (int, float)) and ratio > 0:
                        ratios.append(float(ratio))
                # Measured per-family dispatch walls from the run's
                # flight ring (telemetry/flight.py): DISPATCH_OVERHEAD
                # was unfittable analytically, but sealed records carry
                # the real dispatch->fetch seconds per family.
                from ..telemetry.flight import (
                    FLIGHT_FILENAME,
                    family_seconds,
                    read_flight,
                )

                fams = family_seconds(
                    read_flight(ledger.parent / FLIGHT_FILENAME)
                )
                if fams:
                    cal.family_seconds = fams
                    cal.sources.append(f"flight x{len(fams)}")
                # Compiler-reported FLOPs per dispatch per family
                # (`kind:"cost"` ledger records — the roofline plane,
                # telemetry/roofline.py). Joined against the measured
                # walls above, they anchor `efficiency` to compiler
                # ground truth; absent sidecars (legacy run, capture
                # off) leave the analytic/MFU estimate in place.
                from ..telemetry.roofline import cost_flops_by_family

                cost = cost_flops_by_family(
                    read_ledger(ledger, kinds={"cost"})
                )
                if cost:
                    cal.cost_flops = cost
                    cal.sources.append(f"cost_flops x{len(cost)}")
                    anchored = cost_anchored_efficiency(
                        cost,
                        cal.family_seconds,
                        summary.get("peak_bf16_tflops"),
                    )
                    if anchored is not None:
                        cal.efficiency = anchored
                        cal.sources.append("efficiency<-cost_flops")
        if ratios:
            cal.outcome_scale = sum(ratios) / len(ratios)
            cal.sources.append(f"tune_outcome x{len(ratios)}")
        cals.append(cal)
    return merge_calibrations(cals)


def predict_throughput(
    candidate,
    env_config,
    model_config,
    mcts_config,
    lbatch: int,
    calibration: "Calibration | None" = None,
    peak_tflops: "float | None" = None,
    megastep: bool = False,
) -> dict:
    """Predicted steady-state throughput for one candidate.

    Returns {games_per_hour, moves_per_sec, learner_steps_per_sec,
    flops_per_lane_move, dispatches_per_iteration, predicted_mfu,
    moves_per_game, peak_tflops} — the same metric names the live
    `UtilizationMeter` ledgers, so `cli compare` and the tune-outcome
    record align predicted rows against observed ones directly.
    """
    cal = calibration or Calibration()
    f = float(forward_flops(model_config, env_config, env_config.action_dim))
    sims = expected_simulations(mcts_config)
    # Self-play: one leaf eval per simulation + ~one root eval per
    # move; learner: each experience is consumed once (replay ratio 1).
    step_f = float(
        train_step_flops(
            model_config, env_config, env_config.action_dim, lbatch
        )
    )
    flops_per_lane_move = (sims + 1.0) * f + step_f / max(1, lbatch)

    peak = peak_tflops if peak_tflops else FALLBACK_PEAK_TFLOPS
    rate = cal.efficiency * peak * 1e12 * max(1, candidate.dp)
    b, t = candidate.sp_batch, candidate.chunk
    compute_s = b * t * flops_per_lane_move / max(rate, 1e-9)
    # Host launches per iteration: the fused megastep is ONE program;
    # a sync iteration pays rollout + ingest + ceil(steps/K) learner
    # groups (the dispatches_per_iteration gauge the ledger records).
    steps_per_iter = b * t / max(1, lbatch)
    dispatches = (
        1.0
        if megastep
        else 2.0 + math.ceil(steps_per_iter / max(1, candidate.fused_k))
    )
    iter_s = compute_s + dispatches * cal.overhead_s
    lane_moves_per_sec = b * t / iter_s if iter_s > 0 else 0.0
    moves_per_game = (
        cal.moves_per_game
        if isinstance(cal.moves_per_game, (int, float))
        and cal.moves_per_game > 0
        else default_moves_per_game(env_config)
    )
    scale = max(1e-6, cal.outcome_scale)
    moves_per_sec = lane_moves_per_sec * scale
    achieved_flops = moves_per_sec * flops_per_lane_move
    return {
        "games_per_hour": moves_per_sec * 3600.0 / moves_per_game,
        "moves_per_sec": moves_per_sec,
        "learner_steps_per_sec": moves_per_sec / max(1, lbatch),
        "flops_per_lane_move": flops_per_lane_move,
        "dispatches_per_iteration": dispatches,
        "predicted_mfu": achieved_flops
        / (peak * 1e12 * max(1, candidate.dp)),
        "moves_per_game": moves_per_game,
        "peak_tflops": peak,
    }
