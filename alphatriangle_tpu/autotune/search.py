"""Pruned feasibility search: pick the feasible config maximizing
predicted games/hour (docs/AUTOTUNE.md).

The expensive operation is the feasibility oracle — `estimate_fit`
(telemetry/memory.py) AOT-lowers and compiles the candidate's hot
programs to read `compiled.memory_analysis()`, seconds per call, never
executing anything. The search exists to call it as few times as
possible:

1. **Gates** (free): divisibility/geometry constraints reject
   candidates a run would refuse or silently de-shard
   (autotune/space.py).
2. **Ring math** (free): `replay_ring_bytes` is pure dtype/shape
   arithmetic; when the ring's per-device slice alone exceeds the byte
   limit, no program analysis can save the candidate.
3. **Monotone-in-B dominance**: within a (geometry, capacity, T, K,
   dp) group the search walks B descending; the first oracle-confirmed
   B wins the group and every smaller B is dominated unseen — both the
   budget and the predicted throughput are monotone in B.

Group winners then rank by predicted games/h (autotune/model.py). The
oracle is injectable so pruning behavior is unit-testable without a
JAX backend (tests/test_autotune.py)."""

import logging
from dataclasses import dataclass, field

from .model import Calibration, predict_throughput
from .space import (
    STATUS_DOMINATED,
    STATUS_FIT,
    STATUS_GATE,
    STATUS_OVER,
    STATUS_RING,
    Candidate,
    SearchSpace,
    divisibility_gate,
)

logger = logging.getLogger(__name__)


@dataclass
class TuneResult:
    """Outcome of one search: per-candidate rows (dicts with candidate
    axes + status + prediction), the winning candidate (None when the
    space is infeasible), its budget/records, and search accounting."""

    rows: list = field(default_factory=list)
    best: "Candidate | None" = None
    best_prediction: "dict | None" = None
    best_budget: "dict | None" = None
    best_records: list = field(default_factory=list)
    oracle_calls: int = 0
    evaluated: int = 0
    limit_bytes: "float | None" = None

    def feasible_rows(self) -> list:
        return [r for r in self.rows if r["status"] == STATUS_FIT]


def materialize_candidate(candidate, base_env, base_model, base_train, mode):
    """(env, model, train) configs for one candidate.

    Geometry "plan" keeps the resolved plan's board; a named geometry
    swaps the board in and re-derives the model's feature-dim contract
    (`expected_other_features_dim`) exactly as the presets do. The
    train config rebuilds through the constructor so every validator
    the real run would hit also gates the candidate here."""
    from ..config import (
        TrainConfig,
        expected_other_features_dim,
        geometry_preset,
    )

    if candidate.geometry == "plan":
        env = base_env
        model = base_model
    else:
        env = geometry_preset(candidate.geometry)
        model = base_model.model_copy(
            update={
                "OTHER_NN_INPUT_FEATURES_DIM": expected_other_features_dim(
                    env
                )
            }
        )
    model = model.model_copy(
        update={"INFERENCE_PRECISION": candidate.inference_precision}
    )
    kw = base_train.model_dump()
    kw.update(
        SELF_PLAY_BATCH_SIZE=candidate.sp_batch,
        BUFFER_CAPACITY=candidate.capacity,
        ROLLOUT_CHUNK_MOVES=candidate.chunk,
        FUSED_LEARNER_STEPS=candidate.fused_k,
        PER_SAMPLE_BACKEND=candidate.per_sample,
        MIN_BUFFER_SIZE_TO_TRAIN=min(
            base_train.MIN_BUFFER_SIZE_TO_TRAIN, candidate.capacity
        ),
    )
    if mode == "megastep":
        kw.update(
            FUSED_MEGASTEP=True, DEVICE_REPLAY="on", ASYNC_ROLLOUTS=False
        )
    train = TrainConfig(**kw)
    return env, model, train


def candidate_mcts(base_mcts, candidate):
    """The MCTS config a candidate's programs lower with: the base
    search config carrying the candidate's kernel axes."""
    return base_mcts.model_copy(
        update={
            "descent_gather": candidate.descent_gather,
            "backup_update": candidate.backup_update,
            "tree_reuse": candidate.tree_reuse,
        }
    )


def ring_bytes_for(candidate, env, model) -> int:
    """Per-device replay-ring bytes for a candidate — pure shape math
    (telemetry/memory.py `replay_ring_bytes`), no JAX."""
    from ..config import expected_other_features_dim
    from ..telemetry.memory import replay_ring_bytes

    shards = max(1, candidate.dp)
    return replay_ring_bytes(
        candidate.capacity,
        (model.GRID_INPUT_CHANNELS, env.ROWS, env.COLS),
        expected_other_features_dim(env),
        env.action_dim,
        shards=shards,
    ) // shards


def default_oracle(mcts_config, mode, device_replay=None, progress=None):
    """The real feasibility oracle: `estimate_fit` over the candidate's
    hot programs (rollout chunk + fused learner group, + the megastep
    program when that is the loop being tuned). Returns a callable
    (candidate, env, model, train, limit) -> (fits, budget, records).
    `device_replay` defaults to True exactly when tuning the megastep
    loop (which requires the device ring); pass it explicitly when
    tuning a sync loop that still keeps its ring in HBM."""
    ring_on_device = (
        (mode == "megastep") if device_replay is None else bool(device_replay)
    )

    def oracle(candidate, env, model, train, limit):
        from ..telemetry.memory import FIT_OK, estimate_fit, fit_verdict

        programs = {"self_play_chunk", "learner_fused"}
        if mode == "megastep":
            programs.add("megastep")
        report = estimate_fit(
            env,
            model,
            candidate_mcts(mcts_config, candidate),
            train,
            fused_k=candidate.fused_k,
            device_replay=ring_on_device,
            megastep=(mode == "megastep"),
            programs=programs,
            progress=progress,
        )
        budget = report["budget"]
        code, _reason = fit_verdict(budget["total_bytes"], limit)
        return code == FIT_OK, budget, report["records"]

    return oracle


def run_search(
    space: SearchSpace,
    base_env,
    base_model,
    base_mcts,
    base_train,
    limit_bytes: "float | None",
    calibration: "Calibration | None" = None,
    peak_tflops: "float | None" = None,
    mode: str = "sync",
    device_replay=None,
    oracle=None,
    progress=None,
) -> TuneResult:
    """Search the space for the feasible candidate maximizing predicted
    games/h. `oracle` defaults to the `estimate_fit` oracle; tests
    inject a pure-math one. `limit_bytes` None is allowed (the caller
    decides whether that is an error); the oracle then reports
    FIT_UNKNOWN as infeasible, so callers should resolve a limit first.
    """
    cal = calibration or Calibration()
    oracle = oracle or default_oracle(
        base_mcts, mode, device_replay=device_replay, progress=progress
    )

    def say(msg: str) -> None:
        logger.info(msg)
        if progress is not None:
            progress(msg)

    result = TuneResult(limit_bytes=limit_bytes)
    lbatch = base_train.BATCH_SIZE
    min_buffer = base_train.MIN_BUFFER_SIZE_TO_TRAIN
    rows_by_candidate: dict = {}

    def add_row(candidate, status, prediction=None, detail="", budget=None):
        row = {
            "geometry": candidate.geometry,
            "sp_batch": candidate.sp_batch,
            "capacity": candidate.capacity,
            "chunk": candidate.chunk,
            "fused_k": candidate.fused_k,
            "dp": candidate.dp,
            "kernels": candidate.kernels(),
            "status": status,
            "detail": detail,
            "predicted": prediction,
            "budget_total_bytes": (
                budget.get("total_bytes") if budget else None
            ),
        }
        rows_by_candidate[candidate] = row
        return row

    # Group candidates (B descending within each group, courtesy of
    # SearchSpace.candidates()) and predict throughput for every
    # un-gated candidate up front — predictions are microseconds.
    groups: dict = {}
    for cand in space.candidates():
        groups.setdefault(cand.group_key(), []).append(cand)

    group_frontiers = []
    for key, members in groups.items():
        frontier = []
        for cand in members:
            gate_reason = divisibility_gate(cand, lbatch, min_buffer)
            if gate_reason is not None:
                add_row(cand, STATUS_GATE, detail=gate_reason)
                continue
            env, model, train = materialize_candidate(
                cand, base_env, base_model, base_train, mode
            )
            prediction = predict_throughput(
                cand,
                env,
                model,
                base_mcts,
                lbatch,
                calibration=cal,
                peak_tflops=peak_tflops,
                megastep=(mode == "megastep"),
            )
            ring = ring_bytes_for(cand, env, model)
            if limit_bytes is not None and ring > limit_bytes:
                add_row(
                    cand,
                    STATUS_RING,
                    prediction=prediction,
                    detail=(
                        f"ring alone {ring} B > limit {int(limit_bytes)} B"
                    ),
                )
                continue
            frontier.append((cand, env, model, train, prediction))
        if frontier:
            group_frontiers.append((key, frontier))

    # Evaluate every group's frontier (B descending): the first
    # oracle-confirmed B wins the group; smaller Bs are dominated.
    # Candidates sharing an oracle_key — differing only on the
    # memory-neutral kernel axes (autotune/space.py) — reuse one
    # oracle answer, so those axes multiply the lattice for free.
    best = None
    oracle_memo: dict = {}
    for _key, frontier in group_frontiers:
        winner = None
        for cand, env, model, train, prediction in frontier:
            if winner is not None:
                add_row(
                    cand,
                    STATUS_DOMINATED,
                    prediction=prediction,
                    detail=f"B{winner.sp_batch} fits in this group",
                )
                continue
            memo_key = cand.oracle_key()
            cached = oracle_memo.get(memo_key)
            if cached is None:
                result.oracle_calls += 1
                say(f"tune: oracle {cand.label()} ...")
                cached = oracle(cand, env, model, train, limit_bytes)
                oracle_memo[memo_key] = cached
            fits, budget, records = cached
            result.evaluated += 1
            if fits:
                winner = cand
                add_row(
                    cand, STATUS_FIT, prediction=prediction, budget=budget
                )
                if (
                    best is None
                    or prediction["games_per_hour"]
                    > best[4]["games_per_hour"]
                ):
                    best = (cand, env, model, train, prediction, budget, records)
            else:
                add_row(
                    cand,
                    STATUS_OVER,
                    prediction=prediction,
                    budget=budget,
                    detail="over budget",
                )

    if best is not None:
        (cand, _env, _model, _train, prediction, budget, records) = best
        result.best = cand
        result.best_prediction = prediction
        result.best_budget = budget
        result.best_records = records
    result.rows = sorted(
        rows_by_candidate.values(),
        key=lambda r: (
            -(r["predicted"] or {}).get("games_per_hour", 0.0),
            r["geometry"],
            -r["sp_batch"],
        ),
    )
    return result
