"""alphatriangle_tpu — a TPU-native AlphaZero framework for the triangle puzzle.

A ground-up JAX/XLA/Pallas redesign with the capability surface of the
reference `lguibr/alphatriangle` stack (alphatriangle + trianglengin +
trimcts + trieye), built TPU-first:

- The game engine is a vectorized, fully-jittable JAX environment
  (struct-of-arrays state, static shapes) instead of a per-game C++ object
  (reference: trianglengin C++ core, see SURVEY.md §2b).
- MCTS is a batched on-device tree search whose leaf evaluations batch
  across *all* parallel games onto the MXU (reference: trimcts C++ with
  per-worker CPU torch eval, SURVEY.md §3.2).
- The learner is a pure-functional train step sharded over a
  `jax.sharding.Mesh` with XLA collectives (reference: single-process
  torch trainer, alphatriangle/rl/core/trainer.py).
- Stats + persistence are an async host event bus with Orbax
  checkpointing (reference: trieye Ray actor).
"""

__version__ = "0.1.0"

from alphatriangle_tpu.config import (
    AlphaTriangleMCTSConfig,
    EnvConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)

__all__ = [
    "AlphaTriangleMCTSConfig",
    "EnvConfig",
    "MeshConfig",
    "ModelConfig",
    "TrainConfig",
    "__version__",
]
