"""Shared arena-play helpers for strength evaluation.

Used by `cli eval` (checkpoint vs random / head-to-head) and
`benchmarks/elo_ladder.py`. The paired-hands property all arena
comparisons lean on: reset keys are fixed by `seed` and the engine's
shape draws depend only on the step index (the key chain splits every
step regardless of action), so game i sees the same hand sequence under
every policy — comparisons are paired, stripping the hand-luck variance
that dominates this game.

Arena play is a CLIENT of the serving session API
(serving/session.py): games are admitted into a `SessionSlots` array
and stepped through the same masked lockstep path the policy service
dispatches — eval/arena traffic and served "human" traffic exercise
one code path. `play` drives an arbitrary `policy_fn` over the slot
states directly; `play_service` drives paired games through the full
`PolicyService` queue/dispatch path (the route `cli eval` and the Elo
ladder take for search policies). Lane isolation (see session.py)
is what makes the two produce identical trajectories.

Termination is checked every `termination_check_every` moves instead
of every move: the per-move `states.done -> NumPy` sync was a host
round trip per move; stepping all-done lanes is a frozen no-op, so the
deferred check trades a handful of inert dispatches at the end of a
run for a sync-free steady state. Results are bit-identical for any
check interval (test_arena pins this with a fixed seed).
"""

from collections.abc import Callable

import numpy as np

TERMINATION_CHECK_EVERY = 8


def play(
    env,
    policy_fn: Callable,
    games: int,
    max_moves: int,
    seed: int,
    termination_check_every: int = TERMINATION_CHECK_EVERY,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Roll `games` paired hands under `policy_fn(states, move) -> (B,)
    actions`; returns (scores, lengths, done) as NumPy arrays."""
    import jax
    import jax.numpy as jnp

    from .serving.session import SessionSlots

    slots = SessionSlots(env, games)
    slots.admit_many(jax.random.split(jax.random.PRNGKey(seed), games))
    mask = np.ones(games, dtype=bool)
    for move in range(max_moves):
        if move % termination_check_every == 0 and bool(
            np.asarray(slots.states.done).all()
        ):
            break
        actions = policy_fn(slots.states, move)
        slots.step(jnp.asarray(actions, dtype=jnp.int32), mask)
    return slots.host_results()


def play_service(
    service,
    games: int,
    max_moves: int,
    seed: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Paired arena play through the policy service's request-queue +
    dispatch path; same contract and same results as
    `play(env, greedy_mcts_policy(net, mcts), ...)` when the service
    wraps that (net, mcts) — the dispatch keys reproduce
    `greedy_mcts_policy`'s `PRNGKey(7000 + move)` chain and lane
    isolation keeps per-game trajectories independent of churn.

    The service must have at least `games` free slots; sessions are
    retired as their games finish (the service's churn path, exercised
    by every eval)."""
    import jax

    if service.sessions.free_count < games:
        raise RuntimeError(
            f"play_service: {games} games need {games} free slots; "
            f"only {service.sessions.free_count} of "
            f"{service.sessions.slots} free"
        )
    sessions = service.open_sessions(
        jax.random.split(jax.random.PRNGKey(seed), games)
    )
    order = {s.sid: i for i, s in enumerate(sessions)}
    scores = np.zeros(games, dtype=np.float32)
    lengths = np.zeros(games, dtype=np.int32)
    done = np.zeros(games, dtype=bool)

    def close(sid: int) -> None:
        i = order[sid]
        summary = service.close_session(sid)
        scores[i] = summary["score"]
        lengths[i] = summary["moves"]
        done[i] = summary["done"]

    for s in sessions:
        service.request_move(s.sid)
    move = 0
    live = games
    while live > 0 and move < max_moves:
        results = service.dispatch(rng=jax.random.PRNGKey(7000 + move))
        move += 1
        for r in results:
            if r["done"] or move >= max_moves:
                close(r["sid"])
                live -= 1
            else:
                service.request_move(r["sid"])
    # Truncated stragglers (max_moves reached mid-queue).
    for s in list(service.sessions.live_sessions()):
        if s.sid in order:
            close(s.sid)
    return scores, lengths, done


def greedy_mcts_policy(net, mcts, use_gumbel: bool = False) -> Callable:
    """Deterministic play from a search: visit-count argmax (PUCT) or
    the final-candidate selection (Gumbel exploit mode). Reads
    `net.variables` at call time, so one compiled search serves any
    number of weight restores — the hot-reload property the policy
    service leans on (serving/service.py)."""
    import jax

    from .mcts.helpers import select_root_actions

    def policy(states, move):
        out = mcts.search(
            net.variables, states, jax.random.PRNGKey(7000 + move)
        )
        return select_root_actions(out, use_gumbel=use_gumbel)

    return policy
