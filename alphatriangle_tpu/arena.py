"""Shared arena-play helpers for strength evaluation.

Used by `cli eval` (checkpoint vs random / head-to-head) and
`benchmarks/elo_ladder.py`. The paired-hands property all arena
comparisons lean on: reset keys are fixed by `seed` and the engine's
shape draws depend only on the step index (the key chain splits every
step regardless of action), so game i sees the same hand sequence under
every policy — comparisons are paired, stripping the hand-luck variance
that dominates this game.
"""

from collections.abc import Callable

import numpy as np


def play(
    env,
    policy_fn: Callable,
    games: int,
    max_moves: int,
    seed: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Roll `games` paired hands under `policy_fn(states, move) -> (B,)
    actions`; returns (scores, lengths, done) as NumPy arrays."""
    import jax
    import jax.numpy as jnp

    states = env.reset_batch(jax.random.split(jax.random.PRNGKey(seed), games))
    for move in range(max_moves):
        if bool(np.asarray(states.done).all()):
            break
        actions = policy_fn(states, move)
        states, _, _ = env.step_batch(
            states, jnp.asarray(actions, dtype=jnp.int32)
        )
    return (
        np.asarray(states.score),
        np.asarray(states.step_count),
        np.asarray(states.done),
    )


def greedy_mcts_policy(net, mcts, use_gumbel: bool = False) -> Callable:
    """Deterministic play from a search: visit-count argmax (PUCT) or
    the final-candidate selection (Gumbel exploit mode). Reads
    `net.variables` at call time, so one compiled search serves any
    number of weight restores."""
    import jax

    def policy(states, move):
        out = mcts.search(
            net.variables, states, jax.random.PRNGKey(7000 + move)
        )
        if use_gumbel:
            return np.maximum(np.asarray(out.selected_action), 0)
        counts = np.asarray(out.visit_counts)
        return np.where(counts.sum(axis=1) > 0, counts.argmax(axis=1), 0)

    return policy
