"""AOT precompilation of the hot bench/training programs (`cli warm`).

The compile-latency story (docs/COMPILE_CACHE.md): every program the
bench dispatches inside its measurement window — the self-play rollout
chunk (with its embedded PUCT/Gumbel search), the learner step, the
fused K-step group, the device-replay gather variant, the overlapped
dispatch's bigger fused group — can be lowered and compiled BEFORE a
healthy chip window opens, with the executables serialized through
`compile_cache.CompileCache`. A later bench/training process with the
same shapes then deserializes in milliseconds instead of compiling for
the better part of a minute per program.

`warm_bench_programs` builds the exact objects `bench.py` builds (via
the shared `bench_config.resolve_bench_plan`) and pushes each hot
program through `.warm()` in parallel threads — XLA compilation
releases the GIL, so N programs compile concurrently, Podracer-style
(arXiv:2104.06272 amortizes program build cost off the critical path).

`benchmarks/tpu_watch.sh` runs `cli warm` after every successful chip
probe: by the time a window is declared healthy and the sweep starts,
the persistent + AOT caches already hold the sweep's programs.

`cli warm <tuned_preset.json>` warms an autotuned configuration's
shapes instead (the artifact rides in as BENCH_TUNED_PRESET through
the same `resolve_bench_plan` path; docs/AUTOTUNE.md) — the watcher
does this for the tuned preset it just produced, so a tuned run
launched in the same healthy window starts hot.
"""

import concurrent.futures
import logging
import time

logger = logging.getLogger(__name__)


def warm_bench_programs(
    plan,
    jobs: int = 4,
    programs: "set[str] | None" = None,
    progress=None,
) -> dict:
    """AOT-compile the hot programs for one bench plan.

    `programs`: optional name filter (substring match against the rows
    below). `progress`: optional callable(str) for per-program lines.
    Returns {"programs": [...rows...], "stats": CompileCache.stats(),
    "seconds": total wall}.
    """
    import jax

    from .compile_cache import get_compile_cache
    from .env.engine import TriangleEnv
    from .features.core import get_feature_extractor
    from .nn.network import NeuralNetwork
    from .rl import SelfPlayEngine, Trainer

    def say(msg: str) -> None:
        logger.info(msg)
        if progress is not None:
            progress(msg)

    t_start = time.time()
    backend = jax.default_backend()
    cache = get_compile_cache()
    say(
        f"warm: backend={backend} scale={plan.scale} "
        f"batch={plan.sp_batch} chunk={plan.chunk} sims={plan.sims} "
        f"cache={cache.cache_dir}"
    )

    # Exactly the construction sequence run_bench performs — the cache
    # signatures must match the bench's dispatch arguments bit for bit.
    env = TriangleEnv(plan.env)
    extractor = get_feature_extractor(env, plan.model)
    net = NeuralNetwork(plan.model, plan.env, seed=0)
    engine = SelfPlayEngine(
        env, extractor, net, plan.mcts, plan.train, seed=0
    )
    trainer = Trainer(net, plan.train)

    # Learner programs cannot AOT-cache on the CPU backend (reloaded
    # executables return the donated train state unchanged — see the
    # cpu_aot note in rl/trainer.py); report them as skipped instead of
    # as failures so `cli warm cpu/smoke` still exits 0 when everything
    # warmable is warm.
    learner_fn = (lambda fn: fn) if trainer.aot_enabled else (lambda fn: None)
    targets: list[tuple[str, object]] = [
        (
            f"self_play_chunk/t{plan.chunk}",
            lambda: engine.warm_chunk(plan.chunk),
        ),
        (
            f"learner_step/b{plan.lbatch}",
            learner_fn(lambda: trainer.warm_step(plan.lbatch)),
        ),
        (
            f"learner_fused/k{plan.fused_k}",
            learner_fn(
                lambda: trainer.warm_steps(plan.fused_k, plan.lbatch)
            ),
        ),
    ]
    if plan.overlap_k != plan.fused_k and not plan.device_replay:
        targets.append(
            (
                f"learner_fused/k{plan.overlap_k}",
                learner_fn(
                    lambda: trainer.warm_steps(plan.overlap_k, plan.lbatch)
                ),
            )
        )
    if plan.device_replay:
        from .rl.device_buffer import DeviceReplayBuffer

        dev_buffer = DeviceReplayBuffer(
            plan.train,
            grid_shape=(
                plan.model.GRID_INPUT_CHANNELS,
                plan.env.ROWS,
                plan.env.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=plan.env.action_dim,
        )
        targets.append(
            (
                f"learner_from_ring/k{plan.fused_k}",
                learner_fn(
                    lambda: trainer.warm_steps_from(
                        dev_buffer, plan.fused_k, plan.lbatch
                    )
                ),
            )
        )
        if plan.overlap_k != plan.fused_k:
            targets.append(
                (
                    f"learner_from_ring/k{plan.overlap_k}",
                    learner_fn(
                        lambda: trainer.warm_steps_from(
                            dev_buffer, plan.overlap_k, plan.lbatch
                        )
                    ),
                )
            )
    # Fused megastep (rl/megastep.py): the whole iteration as one
    # program. Contains learner steps, so it is CPU-bypassed like the
    # learner family (row reports skipped-cpu there); the runner/ring
    # are only constructed when the warm will actually run.
    mega_fn = None
    if trainer.aot_enabled:
        from .rl.device_buffer import DeviceReplayBuffer
        from .rl.megastep import MegastepRunner

        mega_buffer = DeviceReplayBuffer(
            plan.train,
            grid_shape=(
                plan.model.GRID_INPUT_CHANNELS,
                plan.env.ROWS,
                plan.env.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=plan.env.action_dim,
        )
        runner = MegastepRunner(engine, trainer, mega_buffer, plan.train)
        mega_fn = lambda: runner.warm_megastep(plan.chunk, plan.fused_k)
    targets.append(
        (f"megastep/t{plan.chunk}_k{plan.fused_k}", mega_fn)
    )
    # dp-sharded megastep family (megastep/dp<D>_t<T>_k<K>): when this
    # process has a multi-device mesh and the plan's geometry divides
    # (same gate as training/setup.py), warm the program a sharded run
    # will actually dispatch — mesh-built engine/trainer/ring, because
    # the cache signature covers the shardings.
    from .telemetry.memory import sharded_megastep_dp

    mega_dp = sharded_megastep_dp(plan.train)
    if mega_dp > 1:
        mega_dp_fn = None
        if trainer.aot_enabled:
            from .config.mesh_config import MeshConfig
            from .rl.megastep import MegastepRunner
            from .rl.sharded_device_buffer import ShardedDeviceReplayBuffer

            mesh = MeshConfig(DP_SIZE=mega_dp).build_mesh()
            dp_engine = SelfPlayEngine(
                env, extractor, net, plan.mcts, plan.train, seed=0,
                mesh=mesh,
            )
            dp_trainer = Trainer(net, plan.train, mesh=mesh)
            dp_ring = ShardedDeviceReplayBuffer(
                plan.train,
                grid_shape=(
                    plan.model.GRID_INPUT_CHANNELS,
                    plan.env.ROWS,
                    plan.env.COLS,
                ),
                other_dim=extractor.other_dim,
                action_dim=plan.env.action_dim,
                mesh=mesh,
            )
            dp_runner = MegastepRunner(
                dp_engine, dp_trainer, dp_ring, plan.train
            )
            mega_dp_fn = lambda: dp_runner.warm_megastep(
                plan.chunk, plan.fused_k
            )
        targets.append(
            (
                f"megastep/dp{mega_dp}_t{plan.chunk}_k{plan.fused_k}",
                mega_dp_fn,
            )
        )
    # Policy-service search shape (serving/service.py): warming
    # `serve/b<B>` is what turns `cli serve` startup from a flagship
    # search compile into a ~0.5s deserialize. The search program has
    # no donated buffers, so (unlike the learner family) its AOT
    # artifacts are safe on every backend. The service's search kind
    # follows the plan's root-selection recipe: Gumbel recipes serve
    # exploit-mode Gumbel (the deterministic arm `cli eval --gumbel`
    # and `cli serve --gumbel` dispatch), PUCT recipes serve PUCT.
    if plan.serve_batch > 0:
        from .serving import PolicyService

        serve_gumbel = (
            getattr(plan.mcts, "root_selection", "puct") == "gumbel"
        )
        if serve_gumbel:
            from .mcts import GumbelMCTS

            serve_mcts = GumbelMCTS(
                env, extractor, net.model, plan.mcts, net.support,
                exploit=True,
            )
        else:
            from .mcts import BatchedMCTS

            serve_mcts = BatchedMCTS(
                env, extractor, net.model, plan.mcts, net.support
            )
        serve_service = PolicyService(
            env,
            extractor,
            net,
            serve_mcts,
            slots=plan.serve_batch,
            use_gumbel=serve_gumbel,
            ladder=plan.serve_buckets,
        )
        # One row per ladder rung (serving/buckets.py): the
        # micro-batcher promises zero-recompile rung switches, which
        # only holds if EVERY rung's program is warmed up front — for
        # the active inference precision (the precision digest keys the
        # cache entries apart).
        for rung in serve_service.ladder.rungs:
            targets.append(
                (
                    f"serve/b{rung}",
                    lambda r=rung: serve_service.warm_rung(r),
                )
            )
    if programs:
        targets = [
            (name, fn)
            for name, fn in targets
            if any(p in name for p in programs)
        ]

    def run_one(name: str, fn) -> dict:
        t0 = time.time()
        if fn is None:
            status = "skipped-cpu"
        else:
            try:
                aot = bool(fn())
                status = "aot" if aot else "jit-fallback"
            except Exception as exc:  # a warm failure must not kill the rest
                logger.exception("warm: %s failed", name)
                status = f"error: {type(exc).__name__}: {exc}"
        dt = time.time() - t0
        say(f"warm: {name}: {status} ({dt:.1f}s)")
        return {"program": name, "status": status, "seconds": round(dt, 1)}

    # Parallel lower+compile: XLA releases the GIL during compilation,
    # so distinct programs genuinely overlap.
    with concurrent.futures.ThreadPoolExecutor(
        max_workers=max(1, jobs)
    ) as pool:
        futures = [pool.submit(run_one, name, fn) for name, fn in targets]
        rows = [f.result() for f in futures]

    stats = cache.stats()
    total = time.time() - t_start
    say(
        f"warm: done in {total:.1f}s — {stats['hits']} hit(s), "
        f"{stats['misses']} miss(es) now serialized for the next process"
    )
    return {"programs": rows, "stats": stats, "seconds": round(total, 1)}
