"""Core graftlint types: findings, pragmas, parsed modules.

The analyzer is a pure-stdlib `ast` pass (plus `telemetry.flight`'s
family table, itself JAX-free): like `cli mem` and `cli doctor` it must
run beside a wedged chip, inside the tpu_watch.sh preflight, and in CI
images without an accelerator stack — importing jax here would defeat
all three. tests/test_analysis.py pins the no-jax contract with a
subprocess import guard.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from pathlib import Path

# Modules whose per-iteration loops are dispatch-latency critical: a
# stray host sync here stalls the device pipeline (the PR 6 arena bug
# class). Directories cover the device subsystems; the two named files
# are the host orchestrators whose bodies run once per iteration.
HOT_PATH_DIRS = ("rl", "mcts", "serving", "ops")
HOT_PATH_FILES = ("training/loop.py", "league/flywheel.py")

# Modules whose code runs under (or feeds) jit: randomness here must go
# through jax PRNG keys or an explicit seeded np Generator — global-
# state RNG (`np.random.*`, stdlib `random`) is invisible to the
# compile cache key and unreproducible across dispatch orders.
DEVICE_CODE_DIRS = ("rl", "mcts", "serving", "ops", "nn", "env", "parallel")

_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*allow\(([\w\-, ]+)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # root-relative posix path
    line: int
    col: int
    message: str
    context: str = "<module>"  # enclosing def/class qualname

    @property
    def key(self) -> str:
        """Baseline identity: stable across line drift (keys on the
        enclosing scope + the offending line's text, not its number)."""
        return f"{self.rule}:{self.path}:{self.context}:{self.text_hash}"

    # text_hash is attached by the engine once the source is at hand;
    # frozen dataclass -> stash via object.__setattr__ in with_text().
    text_hash: str = ""

    def with_text(self, line_text: str) -> "Finding":
        digest = hashlib.sha1(line_text.strip().encode()).hexdigest()[:10]
        return Finding(
            rule=self.rule,
            path=self.path,
            line=self.line,
            col=self.col,
            message=self.message,
            context=self.context,
            text_hash=digest,
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "context": self.context,
            "key": self.key,
        }


def parse_pragmas(lines: list[str]) -> dict[int, set[str]]:
    """1-based line -> set of allowed rule names.

    `# graftlint: allow(rule-a, rule-b)` on (or immediately above) the
    offending line suppresses those rules there. Free text after the
    closing paren is welcome — state WHY the hazard is deliberate.
    """
    out: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


@dataclass
class Module:
    """One parsed source file plus the lookups every rule needs."""

    path: Path
    relpath: str  # posix, relative to the scan root
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    pragmas: dict[int, set[str]] = field(default_factory=dict)
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)

    @classmethod
    def load(cls, path: Path, root: Path) -> "Module":
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        lines = source.splitlines()
        mod = cls(
            path=path,
            relpath=path.relative_to(root).as_posix(),
            source=source,
            tree=tree,
            lines=lines,
            pragmas=parse_pragmas(lines),
        )
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                mod.parents[child] = parent
        return mod

    # --- classification ---------------------------------------------------

    @property
    def top_dir(self) -> str:
        return self.relpath.split("/", 1)[0] if "/" in self.relpath else ""

    @property
    def is_hot_path(self) -> bool:
        return self.top_dir in HOT_PATH_DIRS or self.relpath in HOT_PATH_FILES

    @property
    def is_device_code(self) -> bool:
        return self.top_dir in DEVICE_CODE_DIRS

    # --- lookups ----------------------------------------------------------

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def enclosing_context(self, node: ast.AST) -> str:
        """Qualname of the innermost enclosing def/class, or <module>."""
        names: list[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        cur: ast.AST = node
        while not isinstance(cur, ast.stmt):
            nxt = self.parents.get(cur)
            if nxt is None:
                break
            cur = nxt
        return cur  # type: ignore[return-value]

    def suppressed(self, finding: Finding, node: ast.AST | None = None) -> bool:
        """Pragma check: the finding line, the line above it, or (for
        multi-line statements) the statement's end line."""
        candidates = {finding.line, finding.line - 1}
        if node is not None:
            end = getattr(node, "end_lineno", None)
            if end:
                candidates.add(end)
        for ln in candidates:
            rules = self.pragmas.get(ln)
            if rules and finding.rule in rules:
                return True
        return False
