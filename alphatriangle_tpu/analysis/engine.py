"""graftlint engine: walk a package tree, run the rule catalog, fold
pragmas + baseline, render text/JSON verdicts.

Exit-code contract (mirrored by `cli lint` and pinned in tests):
  0  clean (no findings, no stale baseline entries)
  1  findings, or stale baseline entries (suppressions may not rot)
  2  parse error (a file that doesn't parse can't be vouched for)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from .baseline import apply_baseline, load_baseline
from .model import Finding, Module
from .rules import RULE_NAMES, RULES

LINT_SCHEMA = "alphatriangle.lint.v1"

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache"}


@dataclass
class LintReport:
    root: str
    files_scanned: int = 0
    rules: list[str] = field(default_factory=list)
    findings: list[Finding] = field(default_factory=list)
    suppressed_pragma: int = 0
    suppressed_baseline: int = 0
    stale_baseline: list[dict] = field(default_factory=list)
    parse_errors: list[dict] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        if self.parse_errors:
            return 2
        if self.findings or self.stale_baseline:
            return 1
        return 0

    def as_dict(self) -> dict:
        # "schema" leads so a human tailing windows.jsonl sees what the
        # blob is before anything else.
        return {
            "schema": LINT_SCHEMA,
            "root": self.root,
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": {
                "pragma": self.suppressed_pragma,
                "baseline": self.suppressed_baseline,
            },
            "stale_baseline": self.stale_baseline,
            "parse_errors": self.parse_errors,
            "exit_code": self.exit_code,
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict())

    def render(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(
                f"{f.path}:{f.line}:{f.col + 1} [{f.rule}] {f.message}"
                f" ({f.context})"
            )
        for e in self.parse_errors:
            lines.append(f"{e['path']}: PARSE ERROR: {e['error']}")
        if self.stale_baseline:
            lines.append(
                "stale baseline entries (match no current finding — "
                "delete them from the baseline file):"
            )
            for e in self.stale_baseline:
                lines.append(
                    f"  {e.get('path')} [{e.get('rule')}] "
                    f"{e.get('key')}"
                )
        verdict = (
            "clean"
            if self.exit_code == 0
            else ("parse error" if self.exit_code == 2 else "dirty")
        )
        lines.append(
            f"graftlint: {verdict} — {len(self.findings)} finding(s), "
            f"{self.suppressed_pragma} pragma-allowed, "
            f"{self.suppressed_baseline} baselined, "
            f"{len(self.stale_baseline)} stale baseline entr"
            f"{'y' if len(self.stale_baseline) == 1 else 'ies'} "
            f"({self.files_scanned} files, "
            f"{len(self.rules)} rules)"
        )
        return "\n".join(lines)


def iter_source_files(root: Path) -> list[Path]:
    return sorted(
        p
        for p in root.rglob("*.py")
        if not any(part in _SKIP_DIRS for part in p.parts)
    )


def run_lint(
    root: Path | str,
    rule_names: "list[str] | None" = None,
    baseline_path: "Path | str | None" = None,
) -> LintReport:
    """Lint every .py under `root` with the selected rules."""
    root = Path(root)
    selected = [
        r for r in RULES if rule_names is None or r.name in rule_names
    ]
    if rule_names is not None:
        unknown = set(rule_names) - set(RULE_NAMES)
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; "
                f"available: {list(RULE_NAMES)}"
            )
    report = LintReport(root=str(root), rules=[r.name for r in selected])
    findings: list[Finding] = []
    for path in iter_source_files(root):
        try:
            mod = Module.load(path, root)
        except SyntaxError as e:
            report.parse_errors.append(
                {
                    "path": path.relative_to(root).as_posix(),
                    "error": f"{e.msg} (line {e.lineno})",
                }
            )
            continue
        report.files_scanned += 1
        for rule in selected:
            for finding in rule.check(mod):
                if mod.suppressed(finding):
                    report.suppressed_pragma += 1
                    continue
                findings.append(finding)
    entries = load_baseline(baseline_path)
    kept, suppressed, stale = apply_baseline(findings, entries)
    report.findings = sorted(
        kept, key=lambda f: (f.path, f.line, f.col, f.rule)
    )
    report.suppressed_baseline = len(suppressed)
    report.stale_baseline = stale
    return report
