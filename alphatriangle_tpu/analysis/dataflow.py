"""Lightweight intraprocedural dataflow for graftlint rules.

Deliberately lexical: statements are ordered by source position, not by
control-flow path. That over-approximates "read after donation" across
branches the same way a human skimming the function does — good enough
to catch the PR 3 bug class without a CFG, and every rule's verdict is
fixture-pinned so the approximation can't drift silently.
"""

from __future__ import annotations

import ast


def attr_path(node: ast.AST) -> str | None:
    """Dotted path of a Name/Attribute chain ("self.states.score"),
    or None for anything that isn't a pure chain."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    """Dotted path of a call's callee, or None."""
    return attr_path(call.func)


def strip_subscript(node: ast.AST) -> ast.AST:
    """x[i][j] -> x (subscripting doesn't change which object syncs)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def literal_positions(node: ast.AST) -> tuple[int, ...]:
    """donate_argnums literal -> positions. Non-literal -> empty."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def string_prefix(node: ast.AST) -> str | None:
    """Best-effort constant prefix of a program-name expression:
    "learner_step" -> itself, f"self_play_chunk/t{n}" -> the leading
    constant, serve_program_name(...) -> "serve/" (the one non-literal
    naming helper the serving stack uses)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    if isinstance(node, ast.Call):
        name = call_name(node) or ""
        if "serve" in name.split(".")[-1]:
            return "serve/"
    return None


def assignment_targets(stmt: ast.stmt) -> list[str]:
    """Dotted paths bound by an assignment statement (tuple targets
    flattened); empty for non-assignments."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: list[str] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(p for e in t.elts if (p := attr_path(e)) is not None)
        else:
            p = attr_path(t)
            if p is not None:
                out.append(p)
    return out


def find_call(node: ast.AST, pred, skip_lambda: bool = True) -> ast.Call | None:
    """First Call under `node` satisfying `pred`, skipping Lambda
    bodies (a lambda factory's inner jit is NOT the assigned value)."""
    for child in _walk(node, skip_lambda):
        if isinstance(child, ast.Call) and pred(child):
            return child
    return None


def _walk(node: ast.AST, skip_lambda: bool):
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if skip_lambda and isinstance(child, ast.Lambda):
                continue
            stack.append(child)


def occurrences_after(
    func: ast.AST, path: str, end_line: int, end_col: int
) -> list[tuple[int, int, bool]]:
    """(line, col, is_store) events for `path` inside `func` strictly
    after (end_line, end_col), in source order. An Attribute chain
    event takes its ctx from the outermost link."""
    events: list[tuple[int, int, bool]] = []
    for node in ast.walk(func):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if attr_path(node) != path:
                continue
            ctx = getattr(node, "ctx", None)
            pos = (node.lineno, node.col_offset)
            if pos <= (end_line, end_col):
                continue
            events.append(
                (node.lineno, node.col_offset, isinstance(ctx, ast.Store))
            )
    events.sort()
    return events


class FunctionFacts:
    """Per-function name classification for placement/host checks."""

    def __init__(self, func: ast.AST):
        self.committed: set[str] = set()  # assigned from jax.device_put
        self.host_known: set[str] = set()  # numpy/device_get/literal-born
        for stmt in ast.walk(func):
            if not isinstance(stmt, ast.Assign):
                continue
            names = assignment_targets(stmt)
            if not names:
                continue
            kind = self._classify(stmt.value)
            if kind == "committed":
                self.committed.update(names)
            elif kind == "host":
                self.host_known.update(names)

    @staticmethod
    def _classify(value: ast.AST) -> str | None:
        if isinstance(value, ast.Call):
            name = call_name(value) or ""
            if name.endswith("device_put"):
                return "committed"
            if name.endswith("device_get"):
                return "host"  # the fetch result lives on host
            root = name.split(".", 1)[0]
            if root in ("np", "numpy"):
                return "host"
        if isinstance(value, (ast.List, ast.Dict, ast.Constant)):
            return "host"
        return None

    def classify_arg(self, arg: ast.AST) -> str | None:
        """committed / host / None(unknown) for one call argument."""
        if isinstance(arg, ast.Call):
            return self._classify(arg)
        if isinstance(arg, (ast.List, ast.Dict, ast.Constant)):
            return "host"
        path = attr_path(strip_subscript(arg))
        if path is None:
            return None
        if path in self.committed:
            return "committed"
        if path in self.host_known:
            return "host"
        return None
