"""graftlint: an AST-based JAX-hazard static analyzer (docs/ANALYSIS.md).

Makes this repo's worst silent bug classes mechanically impossible to
reintroduce: use-after-donation (PR 3), mixed-placement recompiles
(PR 5), host syncs in hot loops (PR 6), unbracketed hot dispatches
(PR 10's flight coverage), debug artifacts, and untracked RNG.

JAX-free by contract — `cli lint` runs in CI images, in the
tpu_watch.sh preflight, and beside a wedged chip, exactly like
`cli mem` / `cli doctor` (pinned by a subprocess import-guard test).
"""

from .baseline import (
    BASELINE_SCHEMA,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from .engine import LINT_SCHEMA, LintReport, run_lint
from .model import Finding, Module
from .rules import RULE_NAMES, RULES

__all__ = [
    "BASELINE_SCHEMA",
    "LINT_SCHEMA",
    "Finding",
    "LintReport",
    "Module",
    "RULES",
    "RULE_NAMES",
    "apply_baseline",
    "load_baseline",
    "run_lint",
    "write_baseline",
]
