"""graftlint rule catalog: the six JAX-hazard classes this repo has
actually been bitten by (docs/ANALYSIS.md has the war stories).

Every rule yields `Finding`s from a parsed `Module`; each has a
fixture-pinned true positive AND a near-miss true negative in
tests/test_analysis.py, so precision is a test contract, not a hope.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..telemetry.flight import program_family
from .dataflow import (
    FunctionFacts,
    assignment_targets,
    attr_path,
    call_name,
    find_call,
    literal_positions,
    occurrences_after,
    string_prefix,
    strip_subscript,
)
from .model import Finding, Module

# The dispatch families PR 10 instrumented (plus the fleet router's
# host-side route bracket): every dispatch of one of these MUST sit
# inside a FlightRecorder intent/seal bracket, or a wedge inside it is
# invisible to `cli doctor`.
FLIGHT_FAMILIES = ("rollout", "learner", "megastep", "serve", "fleet", "reuse")

_NP_FETCH = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_JIT_TAILS = (".jit", ".pjit")
# np.random constructors that ARE tracked (explicit seeded generators).
_TRACKED_NP_RANDOM = {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox"}


class Rule:
    name: str = ""
    description: str = ""

    def check(self, mod: Module) -> Iterator[Finding]:
        raise NotImplementedError


def _finding(rule: Rule, mod: Module, node: ast.AST, message: str) -> Finding:
    f = Finding(
        rule=rule.name,
        path=mod.relpath,
        line=node.lineno,
        col=node.col_offset,
        message=message,
        context=mod.enclosing_context(node),
    )
    return f.with_text(mod.line_text(node.lineno))


def _is_jit_call(call: ast.Call) -> bool:
    name = call_name(call) or ""
    return name == "jit" or name == "pjit" or name.endswith(_JIT_TAILS)


def _donating_jit(node: ast.AST) -> tuple[ast.Call, tuple[int, ...]] | None:
    """The jit/pjit call (with literal donate_argnums) under `node`,
    lambda bodies excluded — a factory's inner jit is not this value."""
    call = find_call(
        node,
        lambda c: _is_jit_call(c)
        and any(k.arg == "donate_argnums" for k in c.keywords),
    )
    if call is None:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            positions = literal_positions(kw.value)
            if positions:
                return call, positions
    return None


class ProgramIndex:
    """Module-wide map of names bound to device programs.

    donating: dotted target path -> donated arg positions (from
    `jax.jit(..., donate_argnums=...)`, directly or nested inside a
    `cache.wrap(...)` RHS, or aliased through a local name).
    wrapped: dotted target path -> program-name prefix for every
    `<cache>.wrap("name", ...)` binding (donating or not).
    """

    def __init__(self, mod: Module):
        self.donating: dict[str, tuple[int, ...]] = {}
        self.wrapped: dict[str, str] = {}
        for stmt in ast.walk(mod.tree):
            if not isinstance(stmt, ast.Assign):
                continue
            names = assignment_targets(stmt)
            if not names:
                continue
            rhs = stmt.value
            donated = _donating_jit(rhs)
            # Alias: `self._p = cache.wrap("x", g)` where g donates.
            if donated is None and isinstance(rhs, ast.Call):
                for arg in rhs.args:
                    p = attr_path(arg)
                    if p in self.donating:
                        donated = (None, self.donating[p])  # type: ignore[assignment]
                        break
            if donated is not None:
                for n in names:
                    self.donating[n] = donated[1]
            wrap = find_call(
                rhs,
                lambda c: isinstance(c.func, ast.Attribute)
                and c.func.attr == "wrap"
                and c.args,
            )
            if wrap is not None:
                prefix = string_prefix(wrap.args[0])
                if prefix:
                    for n in names:
                        self.wrapped[n] = prefix


class UseAfterDonation(Rule):
    name = "use-after-donation"
    description = (
        "A buffer passed at a donated position of a donating program is "
        "read again afterwards — donation invalidated it (the PR 3 "
        "silent-stale-params class)."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        index = ProgramIndex(mod)
        if not index.donating:
            return
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            callee = attr_path(call.func)
            positions = index.donating.get(callee or "")
            if not positions:
                continue
            func = mod.enclosing_function(call)
            if func is None:
                continue
            stmt = mod.enclosing_statement(call)
            rebound = set(assignment_targets(stmt))
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = attr_path(call.args[pos])
                if arg is None or arg in rebound:
                    continue  # expression arg, or rebound in-place
                end = (
                    getattr(stmt, "end_lineno", stmt.lineno),
                    getattr(stmt, "end_col_offset", 0),
                )
                events = occurrences_after(func, arg, end[0], end[1])
                if events and not events[0][2]:  # first event is a Load
                    line, col, _ = events[0]
                    f = Finding(
                        rule=self.name,
                        path=mod.relpath,
                        line=line,
                        col=col,
                        message=(
                            f"`{arg}` was donated to `{callee}` (arg "
                            f"{pos}, line {call.lineno}) and is read here "
                            "afterwards; donation invalidated the buffer "
                            "— rebind the result over it or stop donating"
                        ),
                        context=mod.enclosing_context(call),
                    )
                    yield f.with_text(mod.line_text(line))


class HostSyncInHotPath(Rule):
    name = "host-sync-in-hot-path"
    description = (
        "Blocking device->host sync inside a dispatch-latency-critical "
        "module (.item(), block_until_ready, jax.device_get, shape-only "
        "np.asarray, fragmented np.asarray fetches of device state)."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        if not mod.is_hot_path:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth == "item" and not node.args:
                    yield _finding(
                        self,
                        mod,
                        node,
                        ".item() forces a blocking device sync per scalar "
                        "— batch the fetch (one jax.device_get) outside "
                        "the hot loop",
                    )
                    continue
                if meth == "block_until_ready":
                    yield _finding(
                        self,
                        mod,
                        node,
                        ".block_until_ready() stalls the dispatch "
                        "pipeline — only benchmarks should fence",
                    )
                    continue
            if name == "jax.device_get" or name == "jax.block_until_ready":
                yield _finding(
                    self,
                    mod,
                    node,
                    f"{name} in a hot module — if this IS the one "
                    "deliberate fetch of the iteration, mark it "
                    "`# graftlint: allow(host-sync-in-hot-path)` with the "
                    "reason; otherwise batch it",
                )
                continue
            # `jax.debug.callback` is the SANCTIONED beacon channel
            # (telemetry/device_stats.py emit_beacon): unordered,
            # non-blocking, fire-and-forget — NOT a host sync; no
            # finding. `io_callback` is different: ordered=True (or a
            # result that feeds the program) serializes the device on
            # the host round-trip — that IS a hot-path sync.
            if name in ("jax.experimental.io_callback", "io_callback"):
                yield _finding(
                    self,
                    mod,
                    node,
                    f"{name} in a hot module blocks the device program "
                    "on a host round-trip — for progress beacons use "
                    "jax.debug.callback(..., ordered=False) "
                    "(telemetry/device_stats.py emit_beacon); keep "
                    "io_callback off the dispatch path",
                )
                continue
            if name in _NP_FETCH:
                parent = mod.parents.get(node)
                if (
                    isinstance(parent, ast.Attribute)
                    and parent.attr == "shape"
                ):
                    yield _finding(
                        self,
                        mod,
                        node,
                        "np.asarray(x).shape transfers the whole array to "
                        "read static metadata — use x.shape directly (no "
                        "sync, works for host and device arrays)",
                    )
                    continue
                if node.args:
                    target = strip_subscript(node.args[0])
                    path = attr_path(target) or ""
                    parts = path.split(".")
                    if parts[0] == "self" and len(parts) >= 3:
                        yield _finding(
                            self,
                            mod,
                            node,
                            f"np.asarray({path}…) fetches device state "
                            "attribute-by-attribute — batch the reads "
                            "into ONE jax.device_get of a tuple",
                        )


class MixedPlacementDispatch(Rule):
    name = "mixed-placement-dispatch"
    description = (
        "A cached-program call site mixing jax.device_put-committed "
        "args with host-fresh args — the uncommitted ones re-place per "
        "call and can silently recompile the program (the PR 5 48s "
        "megastep recompile class)."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        index = ProgramIndex(mod)
        programs = set(index.donating) | set(index.wrapped)
        if not programs:
            return
        for func in ast.walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            facts: FunctionFacts | None = None
            for call in ast.walk(func):
                if not isinstance(call, ast.Call):
                    continue
                callee = attr_path(call.func)
                if callee not in programs or len(call.args) < 2:
                    continue
                if facts is None:
                    facts = FunctionFacts(func)
                kinds = [facts.classify_arg(a) for a in call.args]
                if "committed" in kinds and "host" in kinds:
                    committed = [
                        i for i, k in enumerate(kinds) if k == "committed"
                    ]
                    host = [i for i, k in enumerate(kinds) if k == "host"]
                    yield _finding(
                        self,
                        mod,
                        call,
                        f"call to `{callee}` mixes device_put-committed "
                        f"args (positions {committed}) with host args "
                        f"(positions {host}) — commit ALL hot-dispatch "
                        "args up front or the placement mapping changes "
                        "per call and recompiles",
                    )


class UnbracketedHotDispatch(Rule):
    name = "unbracketed-hot-dispatch"
    description = (
        "A hot-family cached program (rollout/learner/megastep/serve) "
        "dispatched outside a FlightRecorder intent/seal bracket — a "
        "wedge inside it would be invisible to `cli doctor`."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        index = ProgramIndex(mod)
        hot = {
            target: prefix
            for target, prefix in index.wrapped.items()
            if program_family(prefix) in FLIGHT_FAMILIES
        }
        if not hot:
            return
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            callee = attr_path(call.func)
            if callee not in hot:
                continue
            if self._bracketed(mod, call):
                continue
            yield _finding(
                self,
                mod,
                call,
                f"`{callee}` dispatches flight family "
                f"'{program_family(hot[callee])}' outside a "
                "flight_span()/flight.begin() bracket — a wedge here "
                "leaves no intent record for `cli doctor` to classify",
            )

    @staticmethod
    def _bracketed(mod: Module, call: ast.Call) -> bool:
        # (a) lexically inside `with flight_span(...)`
        cur = mod.parents.get(call)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        n = call_name(expr) or ""
                        if n.split(".")[-1] == "flight_span":
                            return True
            cur = mod.parents.get(cur)
        # (b) a `<...>flight.begin(...)` earlier in the same function
        # (the async begin/finish seal pattern in rl/trainer.py)
        func = mod.enclosing_function(call)
        if func is None:
            return False
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Call)
                and node.lineno <= call.lineno
                and (call_name(node) or "").endswith("flight.begin")
            ):
                return True
        return False


class DebugArtifact(Rule):
    name = "debug-artifact"
    description = (
        "Debug scaffolding reachable from jitted code: jax.debug.print/"
        "breakpoint recompiles and serializes dispatches; breakpoint()/"
        "pdb wedges an unattended chip window forever."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                modname = (
                    node.module
                    if isinstance(node, ast.ImportFrom)
                    else ",".join(a.name for a in node.names)
                )
                if modname and "pdb" in modname.split(","):
                    yield _finding(
                        self, mod, node, "pdb import — an unattended run "
                        "hitting this wedges the window until the watchdog "
                        "kills it"
                    )
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if name in ("jax.debug.print", "jax.debug.breakpoint"):
                yield _finding(
                    self,
                    mod,
                    node,
                    f"{name} left in device code — it forces host "
                    "callbacks per dispatch and changes the compiled "
                    "program",
                )
            elif name == "breakpoint":
                yield _finding(
                    self, mod, node, "breakpoint() call — hangs any "
                    "non-interactive run"
                )
            elif name.startswith("pdb."):
                yield _finding(
                    self, mod, node, f"{name} call — hangs any "
                    "non-interactive run"
                )


class UntrackedRng(Rule):
    name = "untracked-rng"
    description = (
        "Global-state RNG (np.random.*, stdlib random) in device-code "
        "modules: invisible to compile-cache keys, unreproducible under "
        "dispatch reordering — use jax PRNG keys or a seeded "
        "np.random.default_rng Generator."
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        if not mod.is_device_code:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                names = (
                    [node.module]
                    if isinstance(node, ast.ImportFrom)
                    else [a.name for a in node.names]
                )
                if "random" in names:
                    yield _finding(
                        self,
                        mod,
                        node,
                        "stdlib `random` imported in a device-code module "
                        "— its global state never enters a program key; "
                        "thread a jax PRNG key instead",
                    )
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            parts = name.split(".")
            if (
                len(parts) >= 3
                and parts[0] in ("np", "numpy")
                and parts[1] == "random"
                and parts[2] not in _TRACKED_NP_RANDOM
            ):
                yield _finding(
                    self,
                    mod,
                    node,
                    f"{name} uses numpy's GLOBAL rng — seedable but "
                    "shared across threads and invisible to cache keys; "
                    "use np.random.default_rng(seed) or a jax key",
                )


class UntrappedExit(Rule):
    name = "untrapped-exit"
    description = (
        "Bare sys.exit/os._exit in a hot-path or training module — it "
        "bypasses the emergency-checkpoint/preemption path (loop.run's "
        "finally) and the run dies without spilling state. Exiting is "
        "the watchdog's and the supervisor's job (telemetry/flight.py, "
        "supervise/)."
    )

    # The sanctioned exiters: the dispatch watchdog (os._exit is the
    # POINT — the thread that would run shutdown is the wedged one) and
    # the supervisor parent, which owns process lifecycle.
    _WHITELIST_DIRS = ("supervise",)
    _WHITELIST_FILES = ("telemetry/flight.py",)

    def check(self, mod: Module) -> Iterator[Finding]:
        in_scope = mod.is_hot_path or mod.top_dir == "training"
        if not in_scope:
            return
        if (
            mod.top_dir in self._WHITELIST_DIRS
            or mod.relpath in self._WHITELIST_FILES
        ):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if name in ("sys.exit", "os._exit"):
                yield _finding(
                    self,
                    mod,
                    node,
                    f"{name} in a hot-path/training module skips the "
                    "emergency checkpoint + buffer spill + flight flush "
                    "(loop.run's finally) — return a LoopStatus / raise "
                    "instead and let runner.EXIT_CODES map it",
                )


RULES: tuple[Rule, ...] = (
    UseAfterDonation(),
    HostSyncInHotPath(),
    MixedPlacementDispatch(),
    UnbracketedHotDispatch(),
    DebugArtifact(),
    UntrackedRng(),
    UntrappedExit(),
)

RULE_NAMES = tuple(r.name for r in RULES)
