"""graftlint baseline: grandfathered findings, with rot protection.

The baseline file is a checked-in JSON list of finding keys (see
`Finding.key`: rule + path + enclosing scope + offending-line text
hash, deliberately line-number-free so pure line drift never stales
an entry). A finding matching an entry is suppressed; an entry that no
longer matches ANY finding is STALE and fails the lint (exit 1) — a
suppression must be deleted the moment its hazard is gone, or the file
becomes a place findings go to be forgotten.
"""

from __future__ import annotations

import json
from pathlib import Path

from .model import Finding

BASELINE_SCHEMA = "alphatriangle.lint-baseline.v1"


def load_baseline(path: Path | str | None) -> list[dict]:
    """Entries from a baseline file; [] when absent. Raises ValueError
    on an unreadable/mis-schema'd file — a corrupt baseline silently
    treated as empty would resurface every grandfathered finding."""
    if path is None:
        return []
    p = Path(path)
    if not p.exists():
        return []
    try:
        data = json.loads(p.read_text())
    except json.JSONDecodeError as e:
        raise ValueError(f"baseline {p} is not valid JSON: {e}") from e
    if not isinstance(data, dict) or data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"baseline {p} missing schema '{BASELINE_SCHEMA}' header"
        )
    entries = data.get("entries", [])
    if not isinstance(entries, list):
        raise ValueError(f"baseline {p}: 'entries' must be a list")
    return entries


def apply_baseline(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(kept, suppressed, stale_entries).

    An entry suppresses every finding whose key matches it (a key can
    legitimately match twice — e.g. the same fetch pattern repeated in
    one function body produces identical line text)."""
    keys = {str(e.get("key")) for e in entries}
    kept = [f for f in findings if f.key not in keys]
    suppressed = [f for f in findings if f.key in keys]
    live = {f.key for f in suppressed}
    stale = [e for e in entries if str(e.get("key")) not in live]
    return kept, suppressed, stale


def write_baseline(path: Path | str, findings: list[Finding]) -> None:
    """Grandfather the given findings (sorted, deduped by key)."""
    seen: set[str] = set()
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append(
            {
                "key": f.key,
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                # Advisory only (matching is by key): where it was when
                # grandfathered, so humans can find it.
                "line": f.line,
                "message": f.message,
            }
        )
    Path(path).write_text(
        json.dumps(
            {"schema": BASELINE_SCHEMA, "entries": entries}, indent=2
        )
        + "\n"
    )
