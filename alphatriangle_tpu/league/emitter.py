"""Trajectory emitter: served games become replay-ready harvests.

The RLAX loop shape (arXiv:2512.06392): actors stream staleness-tagged
trajectories into the learner's replay path while the learner
broadcasts params back on the step clock. Here the "actor" is the
policy service — every move served through `PolicyService.dispatch`
can be harvested as a `(state features, visit-count policy, outcome)`
row in exactly the layout `ring_scatter`/`add_dense` ingests, tagged
with the hot-reload counter (`PolicyService.weight_reloads`) of the
params that played it.

The emitter is pluggable and off by default: a service without one
behaves byte-for-byte as before (eval, arena, `cli serve` human
traffic), and attaching one makes any serve client a data source.
Completed sessions are packaged as `SelfPlayResult` so the training
loop's `_fold_result` seam — buffer ingest with max-priority PER init,
staleness metrics, telemetry — works unchanged on served data.
"""

import logging
import threading

import numpy as np

from ..mcts.helpers import policy_target_from_visits
from ..rl.types import SelfPlayResult

logger = logging.getLogger(__name__)

_stale_warned = False


class TrajectoryEmitter:
    """Harvests per-move rows from a `PolicyService`'s dispatches.

    Wire by assigning to `service.emitter`; the service calls
    `on_dispatch` once per batched dispatch (pre-step states + search
    output + post-step rewards) and `on_session_close` when a session
    retires. Finished trajectories accumulate until `drain()` (or flow
    to `sink`, when given, as one `SelfPlayResult` per episode)."""

    def __init__(
        self,
        env,
        extractor,
        use_gumbel: bool = False,
        gamma: float = 1.0,
        sink=None,
    ):
        self.env = env
        self.extractor = extractor
        self.use_gumbel = bool(use_gumbel)
        self.gamma = float(gamma)
        self.sink = sink
        # sid -> per-move row lists (grid/other/policy/reward/version).
        self._open: dict[int, dict] = {}
        self._done: list[SelfPlayResult] = []
        self.moves_emitted = 0
        self.episodes_emitted = 0
        # Guards the finished-episode seam: the service thread appends
        # in on_session_close while the learner thread swaps the list
        # in drain(); an unguarded append between drain's read and
        # reset silently lost that episode.
        self._lock = threading.Lock()

    # --- service hooks ----------------------------------------------------

    def on_dispatch(
        self, states, out, served, rewards_np, dones_np, version: int
    ) -> None:
        """One batched dispatch: `states` are the PRE-step session
        states (the positions the search ran on), `served` the Session
        handles served, `version` the service's hot-reload counter —
        the staleness tag every row of this dispatch carries."""
        grids, others = self.extractor.extract_batch(states)
        if self.use_gumbel and getattr(out, "improved_policy", None) is not None:
            policy = out.improved_policy
        else:
            policy = policy_target_from_visits(
                out.visit_counts, self.env.valid_mask_batch(states)
            )
        grids = np.asarray(grids, dtype=np.float32)
        others = np.asarray(others, dtype=np.float32)
        policy = np.asarray(policy, dtype=np.float32)
        for s in served:
            rows = self._open.setdefault(
                s.sid,
                {
                    "grid": [],
                    "other": [],
                    "policy": [],
                    "reward": [],
                    "version": [],
                },
            )
            rows["grid"].append(grids[s.slot])
            rows["other"].append(others[s.slot])
            rows["policy"].append(policy[s.slot])
            rows["reward"].append(float(rewards_np[s.slot]))
            rows["version"].append(int(version))

    def on_session_close(self, sid: int, summary: dict) -> None:
        """Session retired: fold its moves into one episode harvest.
        Value targets are discounted Monte-Carlo outcome returns —
        ret[t] = sum_k gamma^k r[t+k] — the "(features, policy,
        outcome)" tuple of the flywheel contract."""
        rows = self._open.pop(sid, None)
        if not rows or not rows["grid"]:
            return
        rewards = np.asarray(rows["reward"], dtype=np.float32)
        returns = np.empty_like(rewards)
        acc = 0.0
        for t in range(len(rewards) - 1, -1, -1):
            acc = rewards[t] + self.gamma * acc
            returns[t] = acc
        result = SelfPlayResult(
            grid=np.stack(rows["grid"]).astype(np.float32),
            other_features=np.stack(rows["other"]),
            policy_target=np.stack(rows["policy"]),
            value_target=returns,
            episode_scores=[float(summary.get("score", 0.0))],
            episode_lengths=[len(rewards)],
            episode_start_versions=[rows["version"][0]],
            num_episodes=1,
            num_truncated=0 if summary.get("done") else 1,
            trainer_step_at_episode_start=rows["version"][0],
            context={
                "source": "league",
                "row_versions": list(rows["version"]),
            },
        )
        with self._lock:
            self.episodes_emitted += 1
            self.moves_emitted += result.num_experiences
            if self.sink is None:
                self._done.append(result)
        if self.sink is not None:
            self.sink(result)

    # --- harvest ----------------------------------------------------------

    def drain(self) -> "SelfPlayResult | None":
        """All finished episodes since the last drain, merged into one
        dense harvest (None when nothing finished). Safe against a
        concurrent `on_session_close` (the swap happens under the
        emitter lock; the merge itself runs outside it)."""
        with self._lock:
            results, self._done = self._done, []
        return merge_results(results)


def merge_results(results: list) -> "SelfPlayResult | None":
    """Concatenate per-episode harvests into one dense block."""
    results = [r for r in results if r is not None and r.num_experiences]
    if not results:
        return None
    return SelfPlayResult(
        grid=np.concatenate([r.grid for r in results]),
        other_features=np.concatenate([r.other_features for r in results]),
        policy_target=np.concatenate([r.policy_target for r in results]),
        value_target=np.concatenate([r.value_target for r in results]),
        policy_weight=np.concatenate([r.policy_weight for r in results]),
        episode_scores=[s for r in results for s in r.episode_scores],
        episode_lengths=[x for r in results for x in r.episode_lengths],
        episode_start_versions=[
            v for r in results for v in r.episode_start_versions
        ],
        num_episodes=sum(r.num_episodes for r in results),
        num_truncated=sum(r.num_truncated for r in results),
        total_simulations=sum(r.total_simulations for r in results),
        trainer_step_at_episode_start=min(
            r.trainer_step_at_episode_start for r in results
        ),
        context={
            "source": "league",
            "row_versions": [
                v
                for r in results
                for v in r.context.get(
                    "row_versions",
                    [r.trainer_step_at_episode_start] * r.num_experiences,
                )
            ],
        },
    )


def apply_staleness_guard(
    result: "SelfPlayResult | None", clock: int, window: int
) -> "tuple[SelfPlayResult | None, int]":
    """Drop rows whose params version trails `clock` by more than
    `window` reloads: (kept result or None, dropped count).

    The actor-lag guard of the RLAX loop — a session that kept playing
    across many weight broadcasts emits late moves fresh and early
    moves stale; only the stale rows are dropped. Warns once (the
    non-finite drop-counter idiom, rl/device_buffer.py); the cumulative
    count rides the `Stats/stale_dropped` metric and the league ledger
    records."""
    global _stale_warned
    if result is None or window is None or window < 0:
        return result, 0
    versions = np.asarray(
        result.context.get(
            "row_versions",
            [result.trainer_step_at_episode_start] * result.num_experiences,
        ),
        dtype=np.int64,
    )
    if versions.shape[0] != result.num_experiences:
        # Row/version desync (validator dropped rows): keep everything
        # rather than guess an alignment.
        return result, 0
    keep = (int(clock) - versions) <= int(window)
    dropped = int((~keep).sum())
    if dropped == 0:
        return result, 0
    if not _stale_warned:
        _stale_warned = True
        logger.warning(
            "Staleness guard: dropping %d of %d league rows more than "
            "%d reloads behind the learner (warn-once; see "
            "Stats/stale_dropped).",
            dropped,
            result.num_experiences,
            window,
        )
    if keep.sum() == 0:
        return None, dropped
    kept = SelfPlayResult(
        grid=result.grid[keep],
        other_features=result.other_features[keep],
        policy_target=result.policy_target[keep],
        value_target=result.value_target[keep],
        policy_weight=(
            result.policy_weight[keep]
            if result.policy_weight is not None
            else None
        ),
        episode_scores=result.episode_scores,
        episode_lengths=result.episode_lengths,
        episode_start_versions=result.episode_start_versions,
        num_episodes=result.num_episodes,
        num_truncated=result.num_truncated,
        total_simulations=result.total_simulations,
        trainer_step_at_episode_start=result.trainer_step_at_episode_start,
        context={
            **result.context,
            "row_versions": versions[keep].tolist(),
        },
    )
    return kept, dropped
