"""Flywheel loop mode: learner + matchmade league games in one process.

The RLAX topology (arXiv:2512.06392) folded onto one host: the
synchronous training loop keeps its rollout→learn cadence, but a
configured fraction of iterations (`LEAGUE_MIX_RATIO`) plays a round
of matchmade league games through a `PolicyService` instead of a
self-play chunk. Each round:

    broadcast live params ──► live net plays G games (emitter ON)
    matchmaker samples opponent ──► opponent plays G games (emitter OFF)
    win fraction ──► pool Elo update (league.jsonl)
    promotion gate ──► live net checkpoints into the pool on a win streak
    emitter drain ──► staleness guard ──► replay ring (max-priority PER)

Live-game trajectories are harvested by the `TrajectoryEmitter` and
folded through the exact `_fold_result` seam self-play uses, so the
replay ring ingests them with max-priority PER init and the ledger
accounts them; `kind:"league"` records carry the pool/ingest/staleness
story for `cli perf`. The service owns a SEPARATE `NeuralNetwork`
whose weights swap every half-round (`reload_weights`, zero
recompiles) — sharing the learner's net would let an opponent load
corrupt concurrent self-play.
"""

import logging
import time

from ..training.loop import TrainingLoop
from .emitter import TrajectoryEmitter, apply_staleness_guard
from .matchmaker import Matchmaker
from .pool import LEAGUE_FILENAME, LIVE_ID, LeaguePool, pairwise_win_fraction

logger = logging.getLogger(__name__)


def member_variables(checkpoints, template_state, checkpoint_path):
    """Inference variables of a pool member's checkpoint, restored
    WITHOUT touching the trainer (`restore_path` never mutates; the
    elo-ladder's restore→set_state pattern would clobber the learner
    mid-run)."""
    loaded = checkpoints.restore_path(str(checkpoint_path), template_state)
    if loaded.train_state is None:
        raise FileNotFoundError(
            f"league member checkpoint unreadable: {checkpoint_path}"
        )
    variables = {"params": loaded.train_state.params}
    batch_stats = getattr(loaded.train_state, "batch_stats", None)
    if batch_stats is not None:
        variables["batch_stats"] = batch_stats
    return variables


class FlywheelLoop(TrainingLoop):
    """`TrainingLoop` whose sync iterations interleave league rounds.

    Only the synchronous loop composes with a league round (the round
    drives the service between learner steps on one thread);
    `run_flywheel` rejects ASYNC_ROLLOUTS/FUSED_MEGASTEP configs."""

    def __init__(
        self,
        components,
        league_config,
        service,
        emitter: TrajectoryEmitter,
        pool: LeaguePool,
        matchmaker: Matchmaker,
    ):
        super().__init__(components)
        self.league = league_config
        self.service = service
        self.emitter = emitter
        self.pool = pool
        self.matchmaker = matchmaker
        self._mix_acc = 0.0
        self.league_rounds = 0
        self.league_moves_ingested = 0
        self.stale_dropped_total = 0
        # Live-params copy served during league rounds, refreshed from
        # the trainer when RELOAD_EVERY_STEPS learner steps passed.
        self._live_vars = None
        self._live_vars_step: "int | None" = None
        # member_id -> restored variables (bounded; tiny pools hit 100%).
        self._opp_cache: dict = {}

    # --- weights ---------------------------------------------------------

    def _live_variables(self):
        """Deep-copied learner variables (the trainer's are donated by
        its next step; handing them to the serve net live would alias
        freed buffers)."""
        import jax
        import jax.numpy as jnp

        step = self.global_step
        if (
            self._live_vars is None
            or step - self._live_vars_step >= self.league.RELOAD_EVERY_STEPS
        ):
            self._live_vars = jax.tree_util.tree_map(
                jnp.array, self.c.trainer.get_variables()
            )
            self._live_vars_step = step
        return self._live_vars

    def _member_variables(self, member_id: str):
        if member_id not in self._opp_cache:
            if len(self._opp_cache) >= 4:
                self._opp_cache.pop(next(iter(self._opp_cache)))
            self._opp_cache[member_id] = member_variables(
                self.c.checkpoints,
                self.c.trainer.state,
                self.pool.members[member_id]["checkpoint"],
            )
        return self._opp_cache[member_id]

    # --- one league round -------------------------------------------------

    def _league_round(self) -> int:
        """Play one matchmade pairing through the service, fold the
        live side's trajectories into the replay ring. Returns rows
        ingested (the `_fold_result` contract `_run_sync` sizes the
        learner burst with)."""
        from ..arena import play_service

        league = self.league
        svc = self.service
        t0 = time.monotonic()
        seed = (
            self.cfg.RANDOM_SEED + 9001 + 2 * self.league_rounds
        )

        # Live half: fresh params, emitter harvesting.
        svc.reload_weights(self._live_variables())
        svc.emitter = self.emitter
        try:
            live_scores, _, _ = play_service(
                svc, league.GAMES_PER_ROUND, league.MAX_GAME_MOVES, seed
            )
        finally:
            svc.emitter = None

        # Opponent half: a matchmade past checkpoint, no harvesting
        # (its visit policies would train the live net toward an old
        # net's search).
        opponent = self.matchmaker.sample_opponent()
        svc.reload_weights(self._member_variables(opponent))
        opp_scores, _, _ = play_service(
            svc, league.GAMES_PER_ROUND, league.MAX_GAME_MOVES, seed + 1
        )

        win_fraction = pairwise_win_fraction(live_scores, opp_scores)
        self.pool.record_result(LIVE_ID, opponent, win_fraction)
        promoted = self._maybe_promote()

        # Harvest → staleness guard → replay ring.
        harvest = self.emitter.drain()
        harvest, dropped = apply_staleness_guard(
            harvest, svc.weight_reloads, league.STALENESS_WINDOW
        )
        self.stale_dropped_total += dropped
        buffer_before = len(self.c.buffer)
        added = self._fold_result(harvest) if harvest is not None else 0
        self.league_rounds += 1
        self.league_moves_ingested += added
        self.c.stats.log_scalar(
            "Stats/stale_dropped", self.stale_dropped_total, self.global_step
        )
        self._ledger_league(
            opponent=opponent,
            win_fraction=win_fraction,
            promoted=promoted,
            added=added,
            dropped=dropped,
            harvest=harvest,
            buffer_before=buffer_before,
            dt=max(1e-9, time.monotonic() - t0),
        )
        logger.info(
            "League round %d: live %.2f vs %s (elo %.1f vs %.1f), "
            "%d rows ingested%s.",
            self.league_rounds,
            win_fraction,
            opponent,
            self.pool.rating(LIVE_ID),
            self.pool.rating(opponent),
            added,
            f", PROMOTED {promoted}" if promoted else "",
        )
        return added

    def _maybe_promote(self) -> "str | None":
        """Checkpoint + pool-seat the live net when its matchmade
        win-rate clears the gate (cheap pre-check before forcing the
        checkpoint save the pool seat points at)."""
        league = self.league
        rate = self.pool.win_rate(LIVE_ID)
        if (
            self.pool.games.get(LIVE_ID, 0) < league.PROMOTION_MIN_GAMES
            or rate is None
            or rate < league.PROMOTION_WIN_RATE
        ):
            return None
        step = self.global_step
        self._maybe_checkpoint(force=True)
        self.c.checkpoints.wait_until_finished()
        checkpoint = (
            self.c.persistence_config.get_checkpoint_dir().resolve()
            / f"step_{step:08d}"
        )
        return self.pool.maybe_promote(
            str(checkpoint),
            step,
            league.PROMOTION_MIN_GAMES,
            league.PROMOTION_WIN_RATE,
        )

    def _ledger_league(
        self,
        opponent: str,
        win_fraction: float,
        promoted: "str | None",
        added: int,
        dropped: int,
        harvest,
        buffer_before: int,
        dt: float,
    ) -> None:
        """One `kind:"league"` metrics-ledger record per round — the
        pool/ingest/staleness summary `cli perf` folds."""
        ledger = getattr(self.telemetry, "ledger", None)
        if ledger is None:
            return
        clock = self.service.weight_reloads
        versions = (
            harvest.context.get("row_versions", []) if harvest else []
        )
        mean_staleness = (
            round(clock - sum(versions) / len(versions), 3)
            if versions
            else None
        )
        ledger.append(
            {
                "kind": "league",
                "time": time.time(),
                "step": self.global_step,
                "round": self.league_rounds,
                "pool_size": len(self.pool),
                "opponent": opponent,
                "opponent_mix": self.matchmaker.opponent_mix(),
                "win_fraction": round(float(win_fraction), 4),
                "live_elo": round(self.pool.rating(LIVE_ID), 3),
                "promoted": promoted,
                "promotions": self.pool.promotions,
                "moves_ingested": added,
                "ingested_moves_per_sec": round(added / dt, 2),
                "stale_dropped": dropped,
                "stale_dropped_total": self.stale_dropped_total,
                "mean_staleness": mean_staleness,
                "weight_reloads": clock,
                "buffer_size_before": buffer_before,
                "buffer_size_after": len(self.c.buffer),
            }
        )

    # --- the mixed loop ---------------------------------------------------

    def _run_sync(self) -> None:
        cfg = self.cfg
        iteration = 0
        while not self.stop_event.is_set():
            if self._max_steps_reached():
                logger.info(
                    "Reached MAX_TRAINING_STEPS=%d.", cfg.MAX_TRAINING_STEPS
                )
                break
            self.profile.on_iteration(iteration)
            iteration += 1
            # Fractional mix accumulator: RATIO=0.25 plays a league
            # round every 4th iteration, RATIO=1.0 every iteration.
            self._mix_acc += self.league.LEAGUE_MIX_RATIO
            if self._mix_acc >= 1.0 and len(self.pool) > 0:
                self._mix_acc -= 1.0
                with self.profile.phase("league"):
                    added = self._league_round()
            else:
                with self.profile.phase("rollout"):
                    added = self._process_rollout()
            n_steps = cfg.LEARNER_STEPS_PER_ROLLOUT or max(
                1, round(added / cfg.BATCH_SIZE)
            )
            self._run_training_steps(n_steps)
            self._iteration_tail()


def seed_pool_from_run(
    pool: LeaguePool, persistence_config, run_name: str
) -> int:
    """Seed the pool with every checkpoint of an existing run. Member
    ids are namespaced `<run>:step_<n>` so live promotions (which mint
    bare `step_<n>`) never collide with seeds. Returns members added."""
    from ..stats.persistence import CheckpointManager

    src = persistence_config.model_copy(update={"RUN_NAME": run_name})
    mgr = CheckpointManager(src)
    before = len(pool)
    ckpt_dir = src.get_checkpoint_dir().resolve()
    for step in mgr.list_steps():
        pool.add_member(
            f"{run_name}:step_{step:08d}",
            str(ckpt_dir / f"step_{step:08d}"),
            step,
        )
    mgr.close()
    return len(pool) - before


def run_flywheel(
    train_config=None,
    league_config=None,
    env_config=None,
    model_config=None,
    mcts_config=None,
    mesh_config=None,
    persistence_config=None,
    telemetry_config=None,
    pool_from: "str | None" = None,
    log_level: str = "INFO",
    use_tensorboard: bool = True,
) -> int:
    """Run a flywheel session (`cli league`); returns an exit code.

    Mirrors `run_training`'s setup/restore/teardown exactly — a
    flywheel run's checkpoints resume under plain `cli train` — plus:
    the league pool (seeded from `pool_from`'s checkpoints when given),
    a `PolicyService` over its own serve net, and the emitter wiring.
    """
    from ..config.league_config import LeagueConfig
    from ..config.persistence_config import PersistenceConfig
    from ..config.train_config import TrainConfig
    from ..logging_config import setup_logging
    from ..training.runner import EXIT_CODES, _resolve_auto_resume
    from ..training.setup import setup_training_components
    from ..utils.helpers import (
        enable_persistent_compilation_cache,
        enforce_platform,
    )

    setup_logging(log_level)
    train_config = train_config or TrainConfig()
    league_config = league_config or LeagueConfig()
    if train_config.FUSED_MEGASTEP or train_config.ASYNC_ROLLOUTS:
        logger.error(
            "Flywheel mode composes with the synchronous loop only; "
            "disable FUSED_MEGASTEP/ASYNC_ROLLOUTS."
        )
        return 1
    enforce_platform(train_config.DEVICE)
    if train_config.DEVICE_REPLAY == "on" or train_config.FUSED_MEGASTEP:
        # Same latched-flag rule as run_training: forced device replay
        # on the CPU backend needs async dispatch off BEFORE any
        # backend touch (rl/device_buffer.py module docstring).
        import jax

        jax.config.update("jax_cpu_enable_async_dispatch", False)
    persistence_config = persistence_config or PersistenceConfig(
        RUN_NAME=train_config.RUN_NAME
    )
    train_config, persistence_config = _resolve_auto_resume(
        train_config, persistence_config
    )
    import jax

    enable_persistent_compilation_cache(backend=jax.default_backend())

    try:
        components = setup_training_components(
            train_config=train_config,
            env_config=env_config,
            model_config=model_config,
            mcts_config=mcts_config,
            mesh_config=mesh_config,
            persistence_config=persistence_config,
            telemetry_config=telemetry_config,
            use_tensorboard=use_tensorboard,
        )
    except Exception:
        logger.exception("Component setup failed.")
        return 1
    c = components

    # League pool: crash-safe league.jsonl beside the run's metrics
    # ledger; replay restores ratings across restarts.
    run_dir = c.persistence_config.get_run_base_dir()
    pool = LeaguePool(
        run_dir / LEAGUE_FILENAME, elo_k=league_config.ELO_K
    )
    if pool_from:
        added = seed_pool_from_run(pool, c.persistence_config, pool_from)
        logger.info(
            "League pool: seeded %d member(s) from run '%s' (%d total).",
            added,
            pool_from,
            len(pool),
        )
    if len(pool) == 0:
        logger.error(
            "League pool is empty: pass --pool-from a run with "
            "checkpoints (matchmaking needs at least one opponent)."
        )
        c.stats.close()
        c.checkpoints.close()
        return 1
    matchmaker = Matchmaker(
        pool,
        temperature=league_config.MATCH_TEMPERATURE,
        exploration_floor=league_config.EXPLORATION_FLOOR,
        seed=train_config.RANDOM_SEED,
    )

    # The league service: its OWN net (weights swap every half-round;
    # sharing c.net would corrupt concurrent self-play), the learner's
    # env/extractor/search config, telemetry=None (the training loop
    # owns the util-tick clock) but the run's flight recorder so league
    # dispatches seal `serve/b<B>` records for cli doctor/watch.
    from ..mcts import BatchedMCTS
    from ..nn.network import NeuralNetwork
    from ..serving import PolicyService

    serve_net = NeuralNetwork(
        c.model_config, c.env_config, seed=train_config.RANDOM_SEED + 7
    )
    serve_mcts = BatchedMCTS(
        c.env, c.extractor, serve_net.model, c.mcts_config, serve_net.support
    )
    service = PolicyService(
        c.env,
        c.extractor,
        serve_net,
        serve_mcts,
        slots=league_config.LEAGUE_SLOTS,
        telemetry=None,
        rng_seed=train_config.RANDOM_SEED + 11,
    )
    service.flight = getattr(c.telemetry, "flight", None)
    emitter = TrajectoryEmitter(
        c.env, c.extractor, use_gumbel=False, gamma=train_config.GAMMA
    )

    loop = FlywheelLoop(
        components, league_config, service, emitter, pool, matchmaker
    )
    try:
        if train_config.LOAD_CHECKPOINT_PATH:
            loaded = c.checkpoints.restore_path(
                train_config.LOAD_CHECKPOINT_PATH, c.trainer.state
            )
        else:
            loaded = c.checkpoints.restore(c.trainer.state, buffer=c.buffer)
        if loaded.train_state is not None:
            c.trainer.set_state(loaded.train_state)
            c.trainer.sync_to_network()
            loop.set_initial_state(
                loaded.global_step,
                int(loaded.counters.get("episodes_played", 0)),
                int(loaded.counters.get("total_simulations", 0)),
            )
            loop.weight_updates = int(
                loaded.counters.get("weight_updates", 0)
            )
            logger.info(
                "Flywheel resumed at step %d (pool %d, live elo %.1f).",
                loaded.global_step,
                len(pool),
                pool.rating(LIVE_ID),
            )
    except Exception:
        logger.exception(
            "State restore failed for run '%s'; aborting rather than "
            "writing a fresh model into its run directory.",
            train_config.RUN_NAME,
        )
        return 1

    status = loop.run()
    c.stats.close()
    c.checkpoints.close()
    logger.info(
        "Flywheel finished: %s (%d league rounds, %d moves ingested, "
        "%d promotion(s), pool %d).",
        status.value,
        loop.league_rounds,
        loop.league_moves_ingested,
        pool.promotions,
        len(pool),
    )
    return EXIT_CODES[status]
