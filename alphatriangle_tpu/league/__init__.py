"""League subsystem: the experience flywheel (served games → replay).

Three pieces close the serve→replay loop (ROADMAP "Experience
flywheel"): the trajectory emitter harvests `(features, visit policy,
outcome)` rows from `PolicyService` dispatches with staleness tags;
the pool + matchmaker keep a crash-safe `league.jsonl` population of
past checkpoints with Elo ratings and KataGo-style opponent sampling;
the flywheel loop interleaves matchmade league games with self-play
into one learner. See docs/LEAGUE.md.
"""

from .emitter import TrajectoryEmitter, apply_staleness_guard, merge_results
from .matchmaker import Matchmaker
from .pool import (
    INITIAL_ELO,
    LEAGUE_FILENAME,
    LIVE_ID,
    LeaguePool,
    elo_expected,
    fit_elo,
    pairwise_win_fraction,
)

__all__ = [
    "INITIAL_ELO",
    "LEAGUE_FILENAME",
    "LIVE_ID",
    "FlywheelLoop",
    "LeaguePool",
    "Matchmaker",
    "TrajectoryEmitter",
    "apply_staleness_guard",
    "elo_expected",
    "fit_elo",
    "merge_results",
    "pairwise_win_fraction",
    "run_flywheel",
]


def __getattr__(name):
    # flywheel imports jax/training at module load; keep the light
    # pieces (pool/matchmaker/emitter math) importable without it.
    if name in ("FlywheelLoop", "run_flywheel"):
        from . import flywheel

        return getattr(flywheel, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
