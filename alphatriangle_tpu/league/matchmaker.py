"""Rating-proximity matchmaking with an exploration floor.

KataGo-style opponent selection (arXiv:1902.10565): most league games
go to opponents near the live net's rating (those carry the most Elo
information and the most useful training signal), but a uniform
exploration floor keeps every pool member in rotation so a forgotten
weakness — an old checkpoint the live net suddenly loses to — is still
discovered. The opponent-mix histogram feeds the `kind:"league"`
ledger records (`cli perf`'s league line)."""

import numpy as np

from .pool import LIVE_ID, LeaguePool


class Matchmaker:
    """Samples opponents for the live net from a `LeaguePool`."""

    def __init__(
        self,
        pool: LeaguePool,
        temperature: float = 200.0,
        exploration_floor: float = 0.1,
        seed: int = 0,
    ):
        self.pool = pool
        # Elo-gap scale of the proximity kernel: a gap of one
        # `temperature` decays the preference by e^-1.
        self.temperature = max(1e-6, float(temperature))
        self.exploration_floor = min(1.0, max(0.0, float(exploration_floor)))
        self._rng = np.random.default_rng(seed)
        self.opponent_counts: dict[str, int] = {}

    def probabilities(self, live_rating: "float | None" = None) -> dict:
        """Current sampling distribution over pool members."""
        ids = self.pool.member_ids()
        if not ids:
            return {}
        if live_rating is None:
            live_rating = self.pool.rating(LIVE_ID)
        gaps = np.array(
            [abs(self.pool.rating(m) - live_rating) for m in ids]
        )
        prox = np.exp(-gaps / self.temperature)
        total = prox.sum()
        prox = prox / total if total > 0 else np.full(len(ids), 1.0 / len(ids))
        floor = self.exploration_floor
        probs = (1.0 - floor) * prox + floor / len(ids)
        return dict(zip(ids, probs))

    def sample_opponent(self, live_rating: "float | None" = None) -> str:
        """One opponent id, proximity-weighted + floor. Raises on an
        empty pool — seed it before matchmaking."""
        probs = self.probabilities(live_rating)
        if not probs:
            raise RuntimeError(
                "Matchmaker: the league pool is empty; add members first."
            )
        ids = list(probs)
        member = ids[
            self._rng.choice(len(ids), p=np.asarray(list(probs.values())))
        ]
        self.opponent_counts[member] = self.opponent_counts.get(member, 0) + 1
        return member

    def opponent_mix(self) -> dict:
        """Cumulative opponent-selection histogram (ledger field)."""
        return dict(self.opponent_counts)
