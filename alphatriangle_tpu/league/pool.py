"""Persistent league store: a crash-safe `league.jsonl` population.

KataGo (arXiv:1902.10565) trains against a population of its own past
checkpoints; this module is that population's ledger. One append-only
JSONL file per run holds the full league history as events —

- ``{"kind": "member", ...}``    a checkpoint joins the pool
- ``{"kind": "result", ...}``    one finished pairing (win fraction)
- ``{"kind": "rating", ...}``    the Elo updates that result caused
- ``{"kind": "promotion", ...}`` the live net earned a pool seat

so the in-memory state is always a pure replay of the file (the
`MetricsLedger` idiom from telemetry/ledger.py: append one complete
line, tolerate torn tails on read). Ratings use the standard
incremental Elo update — winner's rating never drops on a win — which
is the monotonic-consistency property `benchmarks/league_smoke.py`
gates; the batch Bradley-Terry fit the Elo ladder uses lives here too
(`fit_elo`), so `benchmarks/elo_ladder.py` is a thin client.
"""

import json
import logging
import time
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

LEAGUE_FILENAME = "league.jsonl"

# The conventional id of the training net inside the pool bookkeeping.
# It is never a member until promoted — promotion mints `step_<n>`.
LIVE_ID = "live"

INITIAL_ELO = 0.0


def pairwise_win_fraction(scores_a, scores_b, paired: bool = False) -> float:
    """Win fraction of `a` over `b` from two score samples
    (single-player game: a "match" is a score comparison, the pairing
    rule the Elo ladder established). `paired=True` compares
    element-wise — the same-hands variance reduction the ladder plays
    (identical reset keys per rung); the default compares all pairs
    for independently-dealt samples (flywheel rounds)."""
    a = np.asarray(scores_a, dtype=np.float64)
    b = np.asarray(scores_b, dtype=np.float64)
    if a.size == 0 or b.size == 0:
        return 0.5
    d = a - b if paired and a.shape == b.shape else a[:, None] - b[None, :]
    return float((d > 0).mean() + 0.5 * (d == 0).mean())


def fit_elo(wins: np.ndarray, iters: int = 200, lr: float = 8.0) -> np.ndarray:
    """Batch Bradley-Terry fit in Elo units over a pairwise win-rate
    matrix (diagonal ignored). Extracted from benchmarks/elo_ladder.py;
    callers clip 0/1 winrates before fitting — the MLE is unbounded for
    a never-lost pairing."""
    n = wins.shape[0]
    elo = np.zeros(n)
    for _ in range(iters):
        expected = 1.0 / (
            1.0 + 10 ** ((elo[None, :] - elo[:, None]) / 400.0)
        )
        np.fill_diagonal(expected, 0.0)
        elo += lr * (wins - expected).sum(axis=1)
        elo -= elo.mean()
    return elo


def elo_expected(ra: float, rb: float) -> float:
    return 1.0 / (1.0 + 10 ** ((rb - ra) / 400.0))


class LeaguePool:
    """The checkpoint population + ratings, backed by `league.jsonl`.

    State is rebuilt by replaying the file at construction, so a
    crashed flywheel resumes with the full league intact; every
    mutation appends its event before updating memory."""

    def __init__(self, path: "Path | str", elo_k: float = 32.0):
        self.path = Path(path)
        self.elo_k = float(elo_k)
        # member_id -> {"checkpoint": str, "step": int}
        self.members: dict[str, dict] = {}
        self.ratings: dict[str, float] = {}
        self.games: dict[str, int] = {}  # pairings played per id
        self.win_sum: dict[str, float] = {}  # cumulative win fraction
        self.promotions = 0
        self._replay()

    # --- persistence ------------------------------------------------------

    def _append(self, record: dict) -> None:
        record = {**record, "time": time.time()}
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with self.path.open("a") as f:
                f.write(json.dumps(record, default=str) + "\n")
                f.flush()
        except OSError:
            logger.exception("league append to %s failed", self.path)

    def _replay(self) -> None:
        from ..telemetry.ledger import iter_jsonl_records

        if not self.path.exists():
            return
        for r in iter_jsonl_records(self.path):
            kind = r.get("kind")
            if kind == "member":
                self.members[r["member_id"]] = {
                    "checkpoint": r.get("checkpoint"),
                    "step": r.get("step"),
                }
                self.ratings.setdefault(
                    r["member_id"], float(r.get("elo", INITIAL_ELO))
                )
            elif kind == "result":
                self._fold_result(
                    r["a"], r["b"], float(r["score_a"]), persist=False
                )
            elif kind == "promotion":
                self.promotions += 1
                # Mirror maybe_promote: the live evidence window reset
                # must survive a crash, or a resumed flywheel would
                # re-promote on the already-spent evidence.
                self.games[LIVE_ID] = 0
                self.win_sum[LIVE_ID] = 0.0

    # --- membership -------------------------------------------------------

    def add_member(
        self,
        member_id: str,
        checkpoint: str,
        step: int,
        elo: float = INITIAL_ELO,
    ) -> None:
        """A checkpoint joins the opponent pool (idempotent by id)."""
        if member_id in self.members:
            return
        self.members[member_id] = {"checkpoint": checkpoint, "step": step}
        self.ratings.setdefault(member_id, float(elo))
        self._append(
            {
                "kind": "member",
                "member_id": member_id,
                "checkpoint": str(checkpoint),
                "step": int(step),
                "elo": float(self.ratings[member_id]),
            }
        )

    def member_ids(self) -> list[str]:
        return sorted(self.members, key=lambda m: self.members[m]["step"] or 0)

    def __len__(self) -> int:
        return len(self.members)

    # --- ratings ----------------------------------------------------------

    def rating(self, member_id: str) -> float:
        return self.ratings.get(member_id, INITIAL_ELO)

    def _fold_result(
        self, a: str, b: str, score_a: float, persist: bool
    ) -> tuple[float, float]:
        """One pairing's incremental Elo update: `score_a` is a's win
        fraction over b in [0, 1]. Returns the new (ra, rb)."""
        ra = self.ratings.get(a, INITIAL_ELO)
        rb = self.ratings.get(b, INITIAL_ELO)
        expected = elo_expected(ra, rb)
        delta = self.elo_k * (score_a - expected)
        self.ratings[a] = ra + delta
        self.ratings[b] = rb - delta
        self.games[a] = self.games.get(a, 0) + 1
        self.games[b] = self.games.get(b, 0) + 1
        self.win_sum[a] = self.win_sum.get(a, 0.0) + score_a
        self.win_sum[b] = self.win_sum.get(b, 0.0) + (1.0 - score_a)
        if persist:
            self._append(
                {"kind": "result", "a": a, "b": b, "score_a": float(score_a)}
            )
            for mid in (a, b):
                self._append(
                    {
                        "kind": "rating",
                        "member_id": mid,
                        "elo": round(self.ratings[mid], 3),
                        "games": self.games[mid],
                    }
                )
        return self.ratings[a], self.ratings[b]

    def record_result(self, a: str, b: str, score_a: float) -> tuple[float, float]:
        return self._fold_result(a, b, float(score_a), persist=True)

    def win_rate(self, member_id: str) -> "float | None":
        g = self.games.get(member_id, 0)
        if g == 0:
            return None
        return self.win_sum.get(member_id, 0.0) / g

    # --- promotion --------------------------------------------------------

    def maybe_promote(
        self,
        checkpoint: str,
        step: int,
        min_games: int,
        win_rate_gate: float,
        live_id: str = LIVE_ID,
    ) -> "str | None":
        """Promote the live net into the pool when its matchmade
        win-rate clears the gate over enough pairings (KataGo-style
        gating). Resets the live window so the next promotion is earned
        against fresh evidence. Returns the new member id, or None."""
        games = self.games.get(live_id, 0)
        rate = self.win_rate(live_id)
        if games < min_games or rate is None or rate < win_rate_gate:
            return None
        member_id = f"step_{int(step):08d}"
        if member_id in self.members:
            return None
        self._append(
            {
                "kind": "promotion",
                "member_id": member_id,
                "win_rate": round(rate, 4),
                "games": games,
            }
        )
        self.promotions += 1
        self.add_member(
            member_id, checkpoint, step, elo=self.rating(live_id)
        )
        # Fresh promotion window: win evidence must accumulate anew.
        self.games[live_id] = 0
        self.win_sum[live_id] = 0.0
        return member_id
