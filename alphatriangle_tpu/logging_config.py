"""Root logging setup (reference: `alphatriangle/logging_config.py:10-104`).

Colored, `▲`-prefixed console formatter; optional file handler;
third-party noise clamps (jax/orbax/absl to WARNING).
"""

import logging
import sys
from pathlib import Path

RESET = "\x1b[0m"
COLORS = {
    logging.DEBUG: "\x1b[36m",  # cyan
    logging.INFO: "\x1b[32m",  # green
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[41m",  # red background
}


class TriangleFormatter(logging.Formatter):
    """`▲ [LEVEL] name: msg` with per-level ANSI color."""

    def __init__(self, use_color: bool = True):
        super().__init__()
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        base = (
            f"▲ [{record.levelname}] {record.name}: {record.getMessage()}"
        )
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        if self.use_color and sys.stderr.isatty():
            color = COLORS.get(record.levelno, "")
            return f"{color}{base}{RESET}"
        return base


def setup_logging(
    level: int | str = logging.INFO, log_file: str | Path | None = None
) -> None:
    """Configure the root logger (idempotent: clears prior handlers)."""
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)

    console = logging.StreamHandler(sys.stderr)
    console.setFormatter(TriangleFormatter())
    root.addHandler(console)

    if log_file is not None:
        Path(log_file).parent.mkdir(parents=True, exist_ok=True)
        fh = logging.FileHandler(log_file)
        fh.setFormatter(
            logging.Formatter("%(asctime)s [%(levelname)s] %(name)s: %(message)s")
        )
        root.addHandler(fh)

    # Clamp noisy third-party loggers (reference clamps ray/trimcts).
    for noisy in ("jax", "jax._src", "absl", "orbax", "etils", "numba"):
        logging.getLogger(noisy).setLevel(logging.WARNING)
