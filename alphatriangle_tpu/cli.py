"""Command-line interface (reference `alphatriangle/cli.py:31-326`).

Subcommands mirror the reference's Typer app: `train` (config
overrides -> `run_training`), `tb` (launch TensorBoard on the runs
root), `ml` (MLflow launcher — degrades with a clear message when
MLflow isn't installed, as in this TPU image). The reference's `ray`
command has no equivalent: there is no actor runtime to inspect; the
device story lives in `jax.devices()` (printed by `devices`).

Console script: `alphatriangle-tpu` (pyproject `[project.scripts]`,
reference `pyproject.toml:53-54`).
"""

import argparse
import logging
import subprocess
import sys
from pathlib import Path

logger = logging.getLogger(__name__)


def _add_train_parser(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser("train", help="Run a training session.")
    # Reference override surface (`cli.py:40-74`).
    p.add_argument("--run-name", default=None, help="Run directory name.")
    p.add_argument("--seed", type=int, default=None, help="Random seed.")
    p.add_argument(
        "--log-level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR"],
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="Capture a jax.profiler trace + per-phase timers into "
        "runs/<run>/profile_data/.",
    )
    p.add_argument(
        "--preset",
        default=None,
        metavar="N|PATH",
        help="BASELINE benchmark config 1..5 (config/presets.py) OR a "
        "tuned_preset.json path from `cli tune`; explicit flags below "
        "override preset values. Tuned-preset runs ledger a "
        "predicted-vs-observed tune_outcome record on completion.",
    )
    p.add_argument(
        "--dry-setup",
        action="store_true",
        help="Construct every training component (mesh, network, "
        "buffer, loop threads' inputs) from the resolved config, then "
        "exit 0 without training — proves a tuned preset is runnable.",
    )
    # TPU-native sizing knobs.
    p.add_argument("--max-steps", type=int, default=None)
    p.add_argument("--self-play-batch", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--buffer-capacity", type=int, default=None)
    p.add_argument("--min-buffer", type=int, default=None)
    p.add_argument("--rollout-chunk", type=int, default=None)
    p.add_argument(
        "--fused-learner-steps",
        type=int,
        default=None,
        metavar="K",
        help="Learner steps fused into one device dispatch (1 = exact "
        "per-step PER semantics; >1 collapses host round trips).",
    )
    p.add_argument(
        "--async-rollouts",
        action="store_true",
        help="Overlapped mode: self-play producer thread + replay-ratio"
        "-gated learner (see --replay-ratio).",
    )
    p.add_argument(
        "--device-replay",
        choices=["auto", "on", "off"],
        default=None,
        help="Device-resident replay ring (auto = on for single-chip "
        "accelerator runs): rollouts scatter experiences into device "
        "HBM and batches are gathered there from sampled indices.",
    )
    p.add_argument(
        "--fused-megastep",
        action="store_true",
        help="Anakin-style fused megastep: rollout chunk + ring ingest "
        "+ on-device PER sampling + K learner steps as ONE device "
        "program per iteration; dp-shards over a multi-device mesh "
        "when capacity/batch/lanes divide dp (needs the device ring — "
        "rl/megastep.py, docs/PARALLELISM.md).",
    )
    p.add_argument(
        "--workers",
        type=int,
        default=None,
        metavar="N",
        help="Independent rollout streams in overlapped mode (the "
        "reference's self-play worker count).",
    )
    p.add_argument(
        "--replay-ratio",
        type=float,
        default=None,
        help="Async mode: samples consumed per experience produced.",
    )
    p.add_argument(
        "--fast-sims",
        type=int,
        default=None,
        metavar="S",
        help="Enable playout cap randomization: fast searches use S "
        "sims; only full searches train the policy.",
    )
    p.add_argument(
        "--full-search-prob",
        type=float,
        default=None,
        help="Probability a move runs the full search under playout "
        "cap randomization (default 0.25).",
    )
    p.add_argument(
        "--gumbel",
        action="store_true",
        help="Gumbel root search with sequential halving instead of "
        "PUCT+Dirichlet (stronger at small sim budgets).",
    )
    p.add_argument(
        "--checkpoint-freq",
        type=int,
        default=None,
        metavar="STEPS",
        help="Checkpoint every N learner steps (CHECKPOINT_SAVE_FREQ_STEPS).",
    )
    p.add_argument(
        "--keep-checkpoints",
        type=int,
        default=None,
        metavar="K",
        help="Retain the newest K checkpoints (KEEP_LAST_CHECKPOINTS; "
        "default 5). Raise for post-hoc strength curves over a whole "
        "run's checkpoints.",
    )
    p.add_argument("--no-per", action="store_true")
    p.add_argument(
        "--no-auto-resume",
        action="store_true",
        help="Start fresh instead of resuming the latest run.",
    )
    p.add_argument("--load-checkpoint", default=None, metavar="PATH")
    p.add_argument("--load-buffer", default=None, metavar="PATH")
    p.add_argument("--root-dir", default=None, help="Runs root directory.")
    p.add_argument("--no-tensorboard", action="store_true")
    p.add_argument(
        "--device",
        default=None,
        choices=["auto", "tpu", "cpu"],
        help="Compute platform; cpu forces the CPU backend even when an "
        "accelerator plugin is present.",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="Join a jax.distributed cluster (auto-discovery on TPU "
        "pods; use --coordinator/--num-processes/--process-id for "
        "explicit clusters).",
    )
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument(
        "--no-telemetry",
        action="store_true",
        help="Disable the run-telemetry subsystem (span trace, "
        "health.json heartbeat, stall watchdog, anomaly detection; "
        "docs/OBSERVABILITY.md).",
    )
    p.add_argument(
        "--watchdog-deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="Stall watchdog deadline: no learner step and no rollout "
        "harvest for this long dumps thread stacks + flags the "
        "heartbeat (default 300).",
    )


def merge_train_overrides(base_config, overrides: dict):
    """Apply CLI overrides on top of a preset TrainConfig.

    Rebuilds through the constructor (NOT model_copy) so pydantic
    validation runs, and drops derived schedule lengths when the
    horizon changes so they re-derive instead of keeping the preset's
    values (TrainConfig._derive_schedule_lengths only fills Nones).
    """
    from .config import TrainConfig

    base = base_config.model_dump()
    if "MAX_TRAINING_STEPS" in overrides:
        base.pop("LR_SCHEDULER_T_MAX", None)
        base.pop("PER_BETA_ANNEAL_STEPS", None)
    base.update(overrides)
    return TrainConfig(**base)


def cmd_train(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig, TrainConfig
    from .parallel.distributed import DistributedConfig
    from .training.runner import run_training

    overrides: dict = {}
    if args.run_name is not None:
        overrides["RUN_NAME"] = args.run_name
    if args.seed is not None:
        overrides["RANDOM_SEED"] = args.seed
    if args.max_steps is not None:
        overrides["MAX_TRAINING_STEPS"] = args.max_steps
    if args.self_play_batch is not None:
        overrides["SELF_PLAY_BATCH_SIZE"] = args.self_play_batch
    if args.batch_size is not None:
        overrides["BATCH_SIZE"] = args.batch_size
    if args.buffer_capacity is not None:
        overrides["BUFFER_CAPACITY"] = args.buffer_capacity
    if args.min_buffer is not None:
        overrides["MIN_BUFFER_SIZE_TO_TRAIN"] = args.min_buffer
    if args.rollout_chunk is not None:
        overrides["ROLLOUT_CHUNK_MOVES"] = args.rollout_chunk
    if args.fused_learner_steps is not None:
        overrides["FUSED_LEARNER_STEPS"] = args.fused_learner_steps
    if args.async_rollouts:
        overrides["ASYNC_ROLLOUTS"] = True
    if args.device_replay is not None:
        overrides["DEVICE_REPLAY"] = args.device_replay
    if args.fused_megastep:
        overrides["FUSED_MEGASTEP"] = True
    if args.workers is not None:
        overrides["NUM_SELF_PLAY_WORKERS"] = args.workers
    if args.replay_ratio is not None:
        overrides["REPLAY_RATIO"] = args.replay_ratio
    if args.checkpoint_freq is not None:
        overrides["CHECKPOINT_SAVE_FREQ_STEPS"] = args.checkpoint_freq
    if args.no_per:
        overrides["USE_PER"] = False
    if args.no_auto_resume:
        overrides["AUTO_RESUME_LATEST"] = False
    if args.load_checkpoint is not None:
        overrides["LOAD_CHECKPOINT_PATH"] = args.load_checkpoint
    if args.load_buffer is not None:
        overrides["LOAD_BUFFER_PATH"] = args.load_buffer
    if args.profile:
        overrides["PROFILE_WORKERS"] = True
    if args.device is not None:
        overrides["DEVICE"] = args.device

    telemetry_config = None
    if args.no_telemetry or args.watchdog_deadline is not None:
        from .config import TelemetryConfig

        t_kw: dict = {}
        if args.no_telemetry:
            t_kw["ENABLED"] = False
        if args.watchdog_deadline is not None:
            t_kw["WATCHDOG_DEADLINE_S"] = args.watchdog_deadline
        telemetry_config = TelemetryConfig(**t_kw)

    env_config = model_config = mcts_config = mesh_config = None
    tuned_payload = None
    if args.preset is not None:
        preset = str(args.preset)
        if preset.isdigit():
            from .config import baseline_preset

            bundle = baseline_preset(int(preset), run_name=args.run_name)
        else:
            from .config import load_tuned_preset

            try:
                bundle = load_tuned_preset(preset)
            except ValueError as exc:
                raise SystemExit(f"--preset: {exc}") from exc
            tuned_payload = bundle.get("tuned")
        env_config = bundle["env"]
        model_config = bundle["model"]
        mcts_config = bundle["mcts"]
        mesh_config = bundle["mesh"]
        train_config = merge_train_overrides(bundle["train"], overrides)
    else:
        train_config = TrainConfig(**overrides)

    if (
        args.fast_sims is not None
        or args.full_search_prob is not None
        or args.gumbel
    ):
        from .config import AlphaTriangleMCTSConfig

        mcts_kw = mcts_config.model_dump() if mcts_config else {}
        if args.fast_sims is not None:
            mcts_kw["fast_simulations"] = args.fast_sims
        if args.full_search_prob is not None:
            mcts_kw["full_search_prob"] = args.full_search_prob
        if args.gumbel:
            mcts_kw["root_selection"] = "gumbel"
        if (
            args.full_search_prob is not None
            and mcts_kw.get("fast_simulations") is None
        ):
            raise SystemExit(
                "--full-search-prob has no effect without --fast-sims "
                "(playout cap randomization stays disabled)."
            )
        mcts_config = AlphaTriangleMCTSConfig(**mcts_kw)

    persistence_config = None
    if args.root_dir is not None or args.keep_checkpoints is not None:
        p_kw: dict = {"RUN_NAME": train_config.RUN_NAME}
        if args.root_dir is not None:
            p_kw["ROOT_DATA_DIR"] = args.root_dir
        if args.keep_checkpoints is not None:
            p_kw["KEEP_LAST_CHECKPOINTS"] = args.keep_checkpoints
        persistence_config = PersistenceConfig(**p_kw)
    distributed_config = None
    if args.distributed or args.coordinator is not None:
        distributed_config = DistributedConfig(
            ENABLED=True,
            COORDINATOR_ADDRESS=args.coordinator,
            NUM_PROCESSES=args.num_processes,
            PROCESS_ID=args.process_id,
        )
    rc = run_training(
        train_config=train_config,
        env_config=env_config,
        model_config=model_config,
        mcts_config=mcts_config,
        mesh_config=mesh_config,
        persistence_config=persistence_config,
        distributed_config=distributed_config,
        telemetry_config=telemetry_config,
        log_level=args.log_level,
        use_tensorboard=not args.no_tensorboard,
        dry_setup=args.dry_setup,
    )
    if rc == 0 and tuned_payload is not None and not args.dry_setup:
        # Close the autotuner's loop: ledger predicted-vs-observed so
        # the next `cli tune --calibrate` sharpens its model
        # (docs/AUTOTUNE.md).
        from .autotune import ledger_tune_outcome

        p_cfg = persistence_config or PersistenceConfig(
            RUN_NAME=train_config.RUN_NAME
        )
        record = ledger_tune_outcome(
            p_cfg.get_run_base_dir(), tuned_payload
        )
        if record is not None:
            ratio = record.get("observed_over_predicted")
            print(
                "tune-outcome: observed/predicted games/h = "
                f"{ratio if ratio is not None else 'n/a'} "
                "(ledgered for future `cli tune --calibrate`)."
            )
    return rc


def _launch_ui(tool: str, argv: list[str], module: str | None = None) -> int:
    """Run a dashboard tool in the foreground (reference `cli.py:85-137`).

    `module`: the runnable module when it differs from the import name
    (tensorboard's entry point is tensorboard.main, not the package).
    """
    try:
        __import__(tool)
    except ImportError:
        print(
            f"{tool} is not installed in this environment. "
            f"Install it to use this command.",
            file=sys.stderr,
        )
        return 1
    cmd = [sys.executable, "-m", module or tool, *argv]
    print(f"Launching: {' '.join(cmd)} (Ctrl-C to stop)")
    try:
        return subprocess.call(cmd)
    except KeyboardInterrupt:
        return 0


def cmd_tb(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig

    root = args.root_dir or PersistenceConfig().ROOT_DATA_DIR
    return _launch_ui(
        "tensorboard",
        ["--logdir", root, "--port", str(args.port)],
        module="tensorboard.main",
    )


def cmd_ml(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig

    root = args.root_dir or PersistenceConfig().ROOT_DATA_DIR
    return _launch_ui(
        "mlflow", ["ui", "--backend-store-uri", root, "--port", str(args.port)]
    )


def _resolve_run_dir(
    run_name: str | None, root_dir: str | None
) -> "Path | None":
    """Run directory for a (run name, runs root) pair; latest run when
    the name is omitted. Never imports JAX (safe beside a sick chip)."""
    from .config import PersistenceConfig
    from .stats.watch import find_latest_run_dir

    persistence = PersistenceConfig(RUN_NAME=run_name or "latest")
    if root_dir:
        persistence = persistence.model_copy(
            update={"ROOT_DATA_DIR": root_dir}
        )
    if run_name:
        return persistence.get_run_base_dir()
    run_dir = find_latest_run_dir(persistence.get_runs_root_dir())
    if run_dir is None:
        print(
            f"no runs under {persistence.get_runs_root_dir()}",
            file=sys.stderr,
        )
    return run_dir


def cmd_health(args: argparse.Namespace) -> int:
    """Heartbeat check for a run: pretty-print `health.json` + a
    staleness verdict. Exit 0 = live, 1 = stalled/stale, 2 = no
    heartbeat — so the bench supervisor (or a cron) can gate on it
    without parsing anything."""
    from .telemetry.health import health_verdict, read_health

    run_dir = _resolve_run_dir(args.run, args.root_dir)
    if run_dir is None:
        return 2
    if getattr(args, "probe", False):
        # Machine mode (docs/OBSERVABILITY.md "Probe"): ONE JSON line +
        # the probe exit-code contract (0 live / 1 stalled-or-stale /
        # 2 missing / 3 unsealed dispatch past deadline). The same
        # implementation the fleet router's admission gate uses, so
        # external orchestrators and the fleet agree on readiness.
        import json as _json

        from .telemetry.health import probe_run

        result = probe_run(run_dir, deadline_s=args.deadline)
        print(_json.dumps(result))
        return int(result["code"])
    path = run_dir / "health.json"
    payload = read_health(path)
    if payload is None:
        print(f"no readable heartbeat at {path}", file=sys.stderr)
        return 2
    ok, age, reason = health_verdict(payload, deadline_s=args.deadline)
    verdict = "LIVE" if ok else "STALLED"
    print(f"run {payload.get('run') or run_dir.name}: {verdict} ({reason})")
    print(
        f"  heartbeat    {age:,.0f}s ago (pid {payload.get('pid')}, "
        f"uptime {payload.get('uptime_s', 0):,.0f}s)"
    )
    learner_age = payload.get("learner_age_s")
    rollout_age = payload.get("rollout_age_s")
    print(
        f"  learner      step {payload.get('learner_step', 0):,}"
        + (
            f", last step {learner_age:,.0f}s before the heartbeat"
            if learner_age is not None
            else " (no step yet)"
        )
    )
    print(
        f"  self-play    {payload.get('episodes_played', 0):,} episodes, "
        f"{payload.get('experiences_added', 0):,} experiences"
        + (
            f", last harvest {rollout_age:,.0f}s before the heartbeat"
            if rollout_age is not None
            else ""
        )
    )
    print(
        f"  buffer       {payload.get('buffer_size', 0):,} | stalls "
        f"{payload.get('stall_count', 0)} | deadline "
        f"{payload.get('watchdog_deadline_s')}s"
    )
    for mem in payload.get("device_memory") or []:
        in_use = mem.get("bytes_in_use") or 0
        limit = mem.get("bytes_limit") or 0
        peak = mem.get("peak_bytes_in_use") or 0
        pct = f" ({100.0 * in_use / limit:.0f}%)" if limit else ""
        print(
            f"  device {mem.get('device')} [{mem.get('kind')}]  "
            f"{in_use / 2**30:.2f} GiB in use"
            + (f", peak {peak / 2**30:.2f} GiB" if peak else "")
            + (f" / {limit / 2**30:.2f} GiB{pct}" if limit else "")
        )
    return 0 if ok else 1


def cmd_trace(args: argparse.Namespace) -> int:
    """Summarize a run's host span trace (`trace.json`): per-span-name
    totals, busiest first, plus the file path for Perfetto/chrome
    loading. The spans are wall-clock, so they line up with any
    `--profile` xplane device traces from the same run.

    `--fleet` instead fuses a fleet-parent run dir's evidence — the
    parent's route brackets + fleet.jsonl lifecycle + every replica's
    flight ring and trace.json, clock-calibrated per process — into
    ONE Perfetto timeline with flow arrows following each trace_id
    from router queue-wait to the replica's `serve/b<B>` dispatch
    wall (telemetry/merge.py)."""
    from .telemetry.tracer import summarize_trace_file

    run_dir = _resolve_run_dir(args.run, args.root_dir)
    if run_dir is None:
        return 1
    if args.fleet:
        from .telemetry.merge import merge_fleet_trace

        try:
            result = merge_fleet_trace(run_dir)
        except FileNotFoundError:
            print(
                f"no fleet evidence in {run_dir} (fleet.jsonl missing — "
                "not a fleet-parent run dir?)",
                file=sys.stderr,
            )
            return 1
        print(
            f"merged {result['events']:,} events from "
            f"{result['processes']} process(es), "
            f"{result['replicas']} replica dir(s) -> {result['path']}"
        )
        print(
            f"  route spans {result['route_spans']:,}   "
            f"flow arrows {result['flows']:,} over "
            f"{len(result['flow_trace_ids']):,} trace id(s)"
        )
        print(
            f"\nfull fleet timeline: load {result['path']} in "
            "https://ui.perfetto.dev or chrome://tracing"
        )
        return 0
    path = run_dir / "trace.json"
    try:
        rows = summarize_trace_file(path, top=args.top)
    except (OSError, ValueError) as exc:
        print(f"no readable span trace at {path} ({exc})", file=sys.stderr)
        return 1
    if not rows:
        print(f"{path}: no complete spans recorded.")
        return 0
    width = max(max(len(r["name"]) for r in rows), 5)
    print(
        f"{'span':<{width}}  {'count':>7}  {'total s':>9}  "
        f"{'mean ms':>9}  {'max ms':>9}  {'threads':>7}"
    )
    for r in rows:
        print(
            f"{r['name']:<{width}}  {r['count']:>7d}  "
            f"{r['total_ms'] / 1e3:>9.2f}  {r['mean_ms']:>9.2f}  "
            f"{r['max_ms']:>9.2f}  {r['threads']:>7d}"
        )
    print(
        f"\nfull timeline: load {path} in https://ui.perfetto.dev "
        "or chrome://tracing"
    )
    return 0


def cmd_watch(args: argparse.Namespace) -> int:
    """Live-run console: tail a run's `live_metrics.jsonl` and render
    games/h, learner steps/s, replay ratio, staleness, queue depth —
    the observability the reference served via its Ray dashboard +
    MLflow UI (`alphatriangle/cli.py:301-326`). Never imports JAX, so
    it is safe to run beside a training process on a sick-chip day."""
    import time as _time

    from .stats.watch import (
        FleetWatchState,
        WatchState,
        fleet_line,
        render_frame,
        tail_fleet,
        tail_flight,
        tail_ledger_utils,
        tail_live_metrics,
    )
    from .telemetry.flight import FLIGHT_FILENAME
    from .telemetry.health import read_health

    run_dir = _resolve_run_dir(args.run_name, args.root_dir)
    if run_dir is None:
        return 1
    live = run_dir / "live_metrics.jsonl"
    ledger = run_dir / "metrics.jsonl"
    flight = run_dir / FLIGHT_FILENAME
    fleet_ledger = run_dir / "fleet.jsonl"
    heartbeat = run_dir / "health.json"
    state = WatchState()
    fleet_state = FleetWatchState()
    offset = tail_live_metrics(live, state, 0)
    ledger_offset = tail_ledger_utils(ledger, state, 0)
    flight_offset = tail_flight(flight, state, 0)
    fleet_offset = tail_fleet(fleet_ledger, fleet_state, 0)

    def fleet_extra() -> str:
        """Fleet-parent run dirs get the routing vitals + the SLO
        roll-up appended under the standard frame; training run dirs
        (no fleet.jsonl) render nothing extra."""
        fl = fleet_line(fleet_state)
        if fl is None:
            return ""
        extra = "\n" + fl
        try:
            from .telemetry.slo import evaluate_slos, slo_status_line

            extra += "\n  " + slo_status_line(evaluate_slos(run_dir))
        except Exception:  # the SLO line must never kill the console
            pass
        return extra

    if not live.exists() and not fleet_ledger.exists():
        print(
            f"waiting for {live} (run still starting?) — Ctrl-C to stop",
            file=sys.stderr,
        )
    frame = (
        render_frame(state, run_dir.name, health=read_health(heartbeat))
        + fleet_extra()
    )
    print(frame, flush=True)
    if args.once:
        return 0
    try:
        while True:
            _time.sleep(args.interval)
            offset = tail_live_metrics(live, state, offset)
            ledger_offset = tail_ledger_utils(ledger, state, ledger_offset)
            flight_offset = tail_flight(flight, state, flight_offset)
            fleet_offset = tail_fleet(fleet_ledger, fleet_state, fleet_offset)
            # Redraw in place: move up over the previous frame.
            height = frame.count("\n") + 1
            frame = (
                render_frame(
                    state, run_dir.name, health=read_health(heartbeat)
                )
                + fleet_extra()
            )
            print(f"\x1b[{height}F\x1b[0J" + frame, flush=True)
    except KeyboardInterrupt:
        return 0


def _fmt_cell(value, spec: str = ",.2f", scale: float = 1.0, unit: str = "") -> str:
    if not isinstance(value, (int, float)):
        return "—"
    return f"{value * scale:{spec}}{unit}"


def cmd_perf(args: argparse.Namespace) -> int:
    """Windowed performance summary of a run's metrics ledger: p50/p95
    step time, MFU, throughput and its trend. Reads `metrics.jsonl`
    only — never imports JAX, safe beside a wedged chip. Exit 0 on a
    usable summary, 2 when the ledger is missing or holds no
    utilization records (the schema-gate `make perf-smoke` relies on)."""
    import json as _json

    from .telemetry.ledger import read_ledger, resolve_ledger_path
    from .telemetry.perf import summarize_utilization

    target = Path(args.run) if args.run else None
    if target is not None and target.exists():
        ledger = resolve_ledger_path(target)
    else:
        run_dir = _resolve_run_dir(args.run, args.root_dir)
        if run_dir is None:
            return 2
        ledger = resolve_ledger_path(run_dir)
        if ledger is None:
            print(f"no metrics ledger in {run_dir}", file=sys.stderr)
            return 2
    if ledger is None:
        print(f"no metrics ledger at {args.run}", file=sys.stderr)
        return 2
    # No kinds= pre-filter: summarize_utilization itself tolerates
    # kind-less legacy util ticks that the filter would drop.
    summary = summarize_utilization(
        read_ledger(ledger), window=args.window
    )
    if summary is None:
        print(
            f"{ledger}: no utilization records (run predates the "
            "ledger, or telemetry was disabled)",
            file=sys.stderr,
        )
        return 2
    # Static memory budget rides the summary (compare gates it as
    # `memory_budget_bytes` next to the observed peak).
    mem_budget = None
    mem_records = read_ledger(ledger, kinds={"memory"})
    if mem_records:
        from .telemetry.memory import compose_budget

        budget = compose_budget(mem_records)
        if budget["total_bytes"] > 0:
            mem_budget = budget["total_bytes"]
            summary["memory_budget_bytes"] = mem_budget
    # Per-program device time from the flight recorder's sealed
    # records (telemetry/flight.py): measured dispatch->fetch walls
    # per compiled program, the rows `cli tune --calibrate` feeds on.
    from .telemetry.flight import FLIGHT_FILENAME, read_flight, summarize_flight

    programs = summarize_flight(read_flight(ledger.parent / FLIGHT_FILENAME))
    if programs:
        summary["programs"] = programs
    # League flywheel fold (league/flywheel.py `kind:"league"` records):
    # flywheel runs gain the league_* fields and the league line below.
    from .telemetry.perf import summarize_league

    league = summarize_league(read_ledger(ledger, kinds={"league"}))
    if league is not None:
        summary.update(league)
    # Fleet fold (serving/fleet.py fleet.jsonl decision ledger, beside
    # the metrics ledger): fleet runs gain the fleet_* fields and the
    # fleet line below.
    from .telemetry.perf import summarize_fleet

    fleet_path = ledger.parent / "fleet.jsonl"
    fleet = (
        summarize_fleet(read_ledger(fleet_path))
        if fleet_path.is_file()
        else None
    )
    if fleet is not None:
        summary.update(fleet)
    # Device-stats fold (telemetry/device_stats.py `kind:"device_stats"`
    # records — the in-program stat-packs): ds_* fields + the line
    # below. None on legacy/stats-off ledgers, zero new output then.
    from .telemetry.device_stats import summarize_device_stats

    devstats = summarize_device_stats(
        read_ledger(ledger, kinds={"device_stats"})
    )
    if devstats is not None:
        summary.update(devstats)
    # Roofline fold (telemetry/roofline.py): compiler cost records
    # (`kind:"cost"`) joined against flight-seal walls → roofline_*
    # fields, per-program intensity/bound columns, and the idle line.
    # Gated on cost records existing, so pre-roofline ledgers render
    # with ZERO new fields even though they carry a flight ring.
    roof = None
    cost_records = read_ledger(ledger, kinds={"cost"})
    if cost_records:
        from .telemetry.roofline import summarize_roofline

        roof = summarize_roofline(
            cost_records,
            read_flight(ledger.parent / FLIGHT_FILENAME),
            device_kind=summary.get("device_kind") or "",
            peak_tflops=summary.get("peak_bf16_tflops"),
            trace_path=ledger.parent / "trace.json",
        )
    if roof is not None:
        if roof.get("machine_balance_flops_per_byte") is not None:
            summary["roofline_machine_balance_flops_per_byte"] = roof[
                "machine_balance_flops_per_byte"
            ]
            summary["roofline_peak_hbm_gbps"] = roof.get("peak_hbm_gbps")
        attrib = roof.get("attribution")
        if attrib:
            summary["roofline_chip_idle_fraction"] = attrib.get(
                "chip_idle_fraction"
            )
            summary["roofline_attributed_fraction"] = attrib.get(
                "attributed_fraction"
            )
            summary["roofline_dispatch_s"] = attrib.get("dispatch_s")
            summary["roofline_gap_s"] = attrib.get("gap_s")
            for cat, s in (attrib.get("gaps") or {}).items():
                summary[f"roofline_gap_{cat}_s"] = s
        rows = {r["program"]: r for r in roof.get("programs") or []}
        for p in programs or []:
            r = rows.get(p.get("program"))
            if r is not None:
                p["intensity"] = r.get("intensity")
                p["bound"] = r.get("bound")
                p["roofline_fraction"] = r.get("roofline_fraction")
    if args.json:
        summary["source"] = str(ledger)
        print(_json.dumps(summary))
        return 0
    peak = summary.get("peak_bf16_tflops")
    trend = summary.get("throughput_trend")
    print(f"perf {ledger}")
    print(
        f"  window       {summary['ticks']} tick(s)"
        f" ({summary['ticks_total']} on record),"
        f" steps {summary.get('first_step')}→{summary.get('last_step')},"
        f" {_fmt_cell(summary.get('wall_seconds'), ',.0f', 1, 's')} wall"
    )
    print(
        f"  device       {summary.get('device_kind') or '?'}"
        f"   peak bf16 {_fmt_cell(peak, ',.0f', 1, ' TFLOP/s') if peak else 'unknown'}"
        + (
            f" [{summary.get('peak_source')}]"
            if summary.get("peak_source")
            else ""
        )
    )
    print(
        f"  learner      {_fmt_cell(summary.get('learner_steps_per_sec'))} steps/s"
        f"   step p50 {_fmt_cell(summary.get('step_time_ms_p50'), ',.1f', 1, 'ms')}"
        f"   p95 {_fmt_cell(summary.get('step_time_ms_p95'), ',.1f', 1, 'ms')}"
    )
    print(
        f"  self-play    {_fmt_cell(summary.get('games_per_hour'), ',.1f')} games/h"
        f"   {_fmt_cell(summary.get('moves_per_sec'), ',.1f')} moves/s"
        f"   {_fmt_cell(summary.get('sims_per_sec'), ',.0f')} sims/s"
    )
    print(
        f"  utilization  MFU {_fmt_cell(summary.get('mfu'), ',.2f', 100.0, '%')}"
        f" (max {_fmt_cell(summary.get('mfu_max'), ',.2f', 100.0, '%')})"
        f"   {_fmt_cell(summary.get('tflops_per_sec'))} TFLOP/s"
    )
    print(
        f"  transfers    h2d {_fmt_cell(summary.get('transfer_h2d_ms'), ',.1f', 1, 'ms')}"
        f"   d2h {_fmt_cell(summary.get('transfer_d2h_ms'), ',.1f', 1, 'ms')}"
        f"   buffer fill {_fmt_cell(summary.get('buffer_fill_last'), ',.2f', 100.0, '%')}"
        f"   compile hits {_fmt_cell(summary.get('compile_cache_hit_rate'), ',.0f', 100.0, '%')}"
        f"   dispatch/iter {_fmt_cell(summary.get('dispatches_per_iteration'), ',.1f')}"
    )
    mem_peak = summary.get("mem_peak_bytes_in_use")
    if mem_peak is not None or mem_budget is not None:
        from .telemetry.memory import fmt_bytes as _fmt_bytes

        print(
            f"  memory       peak {_fmt_bytes(mem_peak)}"
            f"   in use {_fmt_bytes(summary.get('mem_bytes_in_use_last'))}"
            f"   limit {_fmt_bytes(summary.get('mem_bytes_limit'))}"
            f"   est budget {_fmt_bytes(mem_budget)} (cli mem)"
        )
    if devstats is not None:
        # In-program search health (device-stats plane): entropy/
        # occupancy are window means, value/occupancy maxes are
        # run-wide excursions.
        print(
            f"  search       entropy {_fmt_cell(summary.get('ds_root_entropy'), ',.2f')}"
            f" (min {_fmt_cell(summary.get('ds_root_entropy_min'), ',.2f')})"
            f"   |v|max {_fmt_cell(summary.get('ds_value_abs_max'), ',.2f')}"
            f"   occupancy {_fmt_cell(summary.get('ds_tree_occupancy'), ',.0f', 100.0, '%')}"
            f" (max {_fmt_cell(summary.get('ds_tree_occupancy_max'), ',.0f', 100.0, '%')})"
            f"   reuse {_fmt_cell(summary.get('ds_reuse_frac'), ',.0f', 100.0, '%')}"
            f"   records {_fmt_cell(summary.get('ds_records'), ',.0f')}"
        )
        if summary.get("ds_grad_norm_max") is not None or summary.get(
            "ds_priority_skew"
        ) is not None:
            print(
                f"  ingest/per   priority skew {_fmt_cell(summary.get('ds_priority_skew'), ',.1f')}"
                f"   IS w min {_fmt_cell(summary.get('ds_is_weight_min'), ',.3f')}"
                f"   grad max {_fmt_cell(summary.get('ds_grad_norm_max'), ',.2f')}"
                f"   update max {_fmt_cell(summary.get('ds_update_norm_max'), ',.3f')}"
            )
    if summary.get("serve_move_latency_ms_p95") is not None:
        # Policy-service SLO line (serving/service.py; docs/SERVING.md):
        # p50 averages tick windows, p95 is the WORST window.
        print(
            f"  serving      move p50 {_fmt_cell(summary.get('serve_move_latency_ms_p50'), ',.1f', 1, 'ms')}"
            f"   p95 {_fmt_cell(summary.get('serve_move_latency_ms_p95'), ',.1f', 1, 'ms')}"
            f"   wait p95 {_fmt_cell(summary.get('serve_queue_wait_ms_p95'), ',.1f', 1, 'ms')}"
            f"   {_fmt_cell(summary.get('serve_requests_per_sec'), ',.1f')} req/s"
            f"   fill {_fmt_cell(summary.get('serve_batch_fill'), ',.0f', 100.0, '%')}"
            f"   reloads {_fmt_cell(summary.get('serve_weight_reloads'), ',.0f')}"
        )
        if summary.get("serve_bucket") is not None:
            # Micro-batcher ladder line (serving/buckets.py): the rung
            # the run ended on, mean wave fill, and switch count.
            print(
                f"  serve ladder bucket b{summary.get('serve_bucket')}"
                f"   fill {_fmt_cell(summary.get('serve_fill'), ',.0f', 100.0, '%')}"
                f"   switches {_fmt_cell(summary.get('serve_rung_switches'), ',.0f')}"
            )
    if league is not None:
        print(
            f"  league       pool {_fmt_cell(summary.get('league_pool_size'), ',.0f')}"
            f"   rounds {_fmt_cell(summary.get('league_rounds'), ',.0f')}"
            f"   ingest {_fmt_cell(summary.get('league_ingested_moves_per_sec'), ',.1f')} moves/s"
            f" ({_fmt_cell(summary.get('league_moves_ingested'), ',.0f')} total)"
            f"   staleness {_fmt_cell(summary.get('league_mean_staleness'), ',.1f')}"
            f"   stale dropped {_fmt_cell(summary.get('league_stale_dropped'), ',.0f')}"
            f"   promotions {_fmt_cell(summary.get('league_promotions'), ',.0f')}"
            f"   live elo {_fmt_cell(summary.get('league_live_elo'), ',.1f')}"
        )
    if fleet is not None:
        # Fleet churn + storm SLOs (serving/fleet.py; fleet.jsonl):
        # latency is end-to-end as the router saw it, retries/hedges
        # included.
        print(
            f"  fleet        move p50 {_fmt_cell(summary.get('fleet_move_latency_ms_p50'), ',.1f', 1, 'ms')}"
            f"   p95 {_fmt_cell(summary.get('fleet_move_latency_ms_p95'), ',.1f', 1, 'ms')}"
            f"   {_fmt_cell(summary.get('fleet_requests_per_sec'), ',.1f')} req/s"
            f"   deaths {_fmt_cell(summary.get('fleet_deaths'), ',.0f')}"
            f"   respawns {_fmt_cell(summary.get('fleet_respawns'), ',.0f')}"
            f"   readmits {_fmt_cell(summary.get('fleet_readmissions'), ',.0f')}"
            f"   sheds {_fmt_cell(summary.get('fleet_sheds'), ',.0f')}"
            f"   lost {_fmt_cell(summary.get('fleet_lost'), ',.0f')}"
        )
    if roof is not None and roof.get("attribution"):
        attrib = roof["attribution"]
        gaps = attrib.get("gaps") or {}
        gap_text = "  ".join(
            f"{cat} {_fmt_cell(s, ',.1f', 1, 's')}"
            for cat, s in gaps.items()
            if isinstance(s, (int, float)) and s > 0
        )
        print(
            f"  roofline     idle {_fmt_cell(attrib.get('chip_idle_fraction'), ',.1f', 100.0, '%')}"
            f"   dispatch {_fmt_cell(attrib.get('dispatch_s'), ',.1f', 1, 's')}"
            f"   attributed {_fmt_cell(attrib.get('attributed_fraction'), ',.1f', 100.0, '%')}"
            + (f"   gaps: {gap_text}" if gap_text else "")
        )
    if programs:
        # Measured per-program device time (flight recorder seals) —
        # busiest first; errors are ok:false seals (failed dispatches).
        # Roofline columns (intensity FLOP/byte, bound, fraction of the
        # roofline ceiling) appear only when cost records exist; rows
        # without a cost sidecar degrade to "—" cells, never raise.
        width = max(max(len(p["program"]) for p in programs), 7)
        head = f"  {'program':<{width}}  {'count':>6}  {'p50':>9}  {'p95':>9}  {'total':>9}  err"
        if roof is not None:
            head += f"  {'intensity':>10}  {'bound':>7}  {'roofline':>8}"
        print(head)
        for p in programs:
            line = (
                f"  {p['program']:<{width}}"
                f"  {p['count']:>6}"
                f"  {_fmt_cell(p['wall_s_p50'], ',.1f', 1e3, 'ms'):>9}"
                f"  {_fmt_cell(p['wall_s_p95'], ',.1f', 1e3, 'ms'):>9}"
                f"  {_fmt_cell(p['wall_s_total'], ',.1f', 1, 's'):>9}"
                f"  {p['errors']}"
            )
            if roof is not None:
                line += (
                    f"  {_fmt_cell(p.get('intensity'), ',.1f'):>10}"
                    f"  {p.get('bound') or '—':>7}"
                    f"  {_fmt_cell(p.get('roofline_fraction'), ',.2f', 100.0, '%'):>8}"
                )
            print(line)
    print(
        f"  trend        {_fmt_cell(trend, '+,.1f', 100.0, '%')} "
        "(2nd-half vs 1st-half throughput)"
    )
    return 0


def cmd_compare(args: argparse.Namespace) -> int:
    """Aligned-metric regression report between two runs (or a run and
    a BENCH_*.json / perf-summary snapshot). Exit 0 = parity or better,
    1 = at least one metric regressed past --threshold, 2 = either side
    unreadable — so a CI job or the bench supervisor can gate on it."""
    import json as _json

    from .telemetry.perf import compare_summaries, load_comparable

    a, label_a = load_comparable(args.run_a, args.root_dir)
    b, label_b = load_comparable(args.run_b, args.root_dir)
    for side, loaded, label in (("A", a, label_a), ("B", b, label_b)):
        if loaded is None:
            print(f"compare: side {side}: {label}", file=sys.stderr)
    if a is None or b is None:
        return 2
    metrics = (
        tuple(m for m in args.metrics.split(",") if m)
        if args.metrics
        else None
    )
    rows, regressions = compare_summaries(
        a, b, threshold=args.threshold, metrics=metrics
    )
    compared = [r for r in rows if r[4] != "n/a"]
    if not compared:
        print(
            "compare: no aligned metrics between the two sides",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(
            _json.dumps(
                {
                    "a": label_a,
                    "b": label_b,
                    "threshold": args.threshold,
                    "rows": [
                        {
                            "metric": m,
                            "a": va,
                            "b": vb,
                            "ratio": ratio,
                            "status": status,
                        }
                        for m, va, vb, ratio, status in rows
                    ],
                    "regressions": regressions,
                }
            )
        )
        return 1 if regressions else 0
    print(f"compare  A = {label_a}")
    print(f"         B = {label_b}   (threshold {args.threshold:.0%})")
    width = max(len(r[0]) for r in rows)
    print(
        f"  {'metric':<{width}}  {'A':>12}  {'B':>12}  {'A/B':>7}  verdict"
    )
    for metric, va, vb, ratio, status in rows:
        print(
            f"  {metric:<{width}}  {_fmt_cell(va, ',.3f'):>12}  "
            f"{_fmt_cell(vb, ',.3f'):>12}  "
            f"{_fmt_cell(ratio, '.3f'):>7}  {status}"
        )
    if regressions:
        print(
            f"REGRESSION: {', '.join(regressions)} worse than baseline "
            f"by more than {args.threshold:.0%}"
        )
        return 1
    print("parity: no metric regressed past the threshold")
    return 0


def cmd_devices(_args: argparse.Namespace) -> int:
    import jax

    from .utils.helpers import enforce_platform

    # Honor JAX_PLATFORMS=cpu even when a site hook re-forces the
    # accelerator plugin (whose init can hang on a sick chip).
    enforce_platform("auto")
    print(f"backend: {jax.default_backend()}")
    for d in jax.devices():
        print(f"  {d.id}: {getattr(d, 'device_kind', d.platform)}")
    return 0


def cmd_analyze(args: argparse.Namespace) -> int:
    from .profiling import analyze_profile_dir

    return analyze_profile_dir(args.profile_dir, top=args.top)


def cmd_eval(args: argparse.Namespace) -> int:
    """Arena evaluation: greedy-MCTS play from a checkpoint, with a
    uniform-random baseline (the reference evaluates strength only via
    training-run score metrics; this makes it a standalone command)."""
    import json as _json

    import numpy as np

    from .utils.helpers import enforce_platform

    enforce_platform(args.device or "auto")

    from .config import (
        AlphaTriangleMCTSConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from .config.run_configs import load_run_configs_or_default
    from .env.engine import TriangleEnv
    from .features.core import get_feature_extractor
    from .mcts import BatchedMCTS
    from .nn.network import NeuralNetwork
    from .rl import Trainer
    from .stats.persistence import CheckpointManager
    from .utils.helpers import enable_persistent_compilation_cache

    # Backend resolves on first device use below anyway; with it known
    # the compile cache gates correctly (eval compiles the same
    # flagship search programs training does — ~70s each cold).
    import jax

    enable_persistent_compilation_cache(backend=jax.default_backend())

    def run_base_dir(run_name: str):
        persistence = PersistenceConfig(RUN_NAME=run_name)
        if args.root_dir:
            persistence = persistence.model_copy(
                update={"ROOT_DATA_DIR": args.root_dir}
            )
        return persistence.get_run_base_dir()

    # Evaluate on the RUN'S OWN board/net configs when available
    # (configs.json in the run dir) — the flagship defaults only apply
    # to runs that actually used them. An explicit --checkpoint without
    # --run-name still has a run dir: checkpoints live at
    # <run>/checkpoints/step_XXXXXXXX, so the run's configs.json sits
    # two parents up from the step directory.
    if args.run_name:
        cfg_dir = run_base_dir(args.run_name)
    elif args.checkpoint:
        cfg_dir = Path(args.checkpoint).resolve().parent.parent
    else:
        cfg_dir = Path("/nonexistent")
    env_cfg, model_cfg = load_run_configs_or_default(cfg_dir)
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=args.sims)
    train_cfg = TrainConfig(RUN_NAME=args.run_name or "eval")

    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)

    def restore_net(
        checkpoint: str | None, run_name: str | None, net_model_cfg=None
    ):
        """Fresh net, optionally restored from a checkpoint path or a
        run's latest checkpoint. Returns (net, source-label)."""
        n = NeuralNetwork(net_model_cfg or model_cfg, env_cfg, seed=0)
        label = "untrained"
        if checkpoint or run_name:
            trainer = Trainer(n, train_cfg)
            persistence = PersistenceConfig(
                RUN_NAME=run_name or "eval_tmp"
            )
            if args.root_dir:
                persistence = persistence.model_copy(
                    update={"ROOT_DATA_DIR": args.root_dir}
                )
            mgr = CheckpointManager(persistence)
            loaded = (
                mgr.restore_path(checkpoint, trainer.state)
                if checkpoint
                else mgr.restore(trainer.state)
            )
            if loaded.train_state is None:
                print("No checkpoint found; evaluating the untrained net.")
            else:
                trainer.set_state(loaded.train_state)
                trainer.sync_to_network()
                label = f"step {loaded.global_step}"
                if run_name and not checkpoint:
                    # Only attribute to the run when the run's own
                    # latest checkpoint was what we restored (an
                    # explicit --checkpoint path wins the ternary and
                    # may come from a different run).
                    label = f"{run_name} {label}"
        return n, label

    def build_search(n, net_model_cfg=None):
        # Each net searches with features built from ITS OWN model
        # config: a --vs-run trained with different feature-affecting
        # settings (e.g. GRID_INPUT_CHANNELS) must not be evaluated on
        # run A's feature layout.
        ext = (
            get_feature_extractor(env, net_model_cfg)
            if net_model_cfg is not None
            else extractor
        )
        if args.gumbel:
            # Gumbel-aware evaluation: exploit mode (no root Gumbel
            # sample) — deterministic argmax of logits + sigma(q).
            from .mcts import GumbelMCTS

            return GumbelMCTS(
                env, ext, n.model, mcts_cfg, n.support, exploit=True
            )
        return BatchedMCTS(env, ext, n.model, mcts_cfg, n.support)

    from .arena import play as arena_play, play_service
    from .serving import PolicyService

    net, source = restore_net(args.checkpoint, args.run_name)
    mcts = build_search(net)
    B = args.games
    rng = np.random.default_rng(args.seed)

    def serve_play(n, m):
        """Search policies run through the policy service's session
        API (serving/service.py): eval traffic and served "human"
        traffic exercise one code path — admit/dispatch/retire over
        the compiled `serve/b<B>` search shape."""
        service = PolicyService(
            env, m.extractor, n, m, slots=B, use_gumbel=args.gumbel
        )
        return play_service(service, B, args.max_moves, args.seed)

    def random_policy(states, move):
        masks = np.asarray(env.valid_mask_batch(states))
        logits = np.where(masks, rng.random(masks.shape), -np.inf)
        return np.where(masks.any(axis=1), logits.argmax(axis=1), 0)

    print(f"Evaluating {source} net: {B} games, {args.sims} sims/move...")
    scores, lengths, done = serve_play(net, mcts)
    r_scores, r_lengths, _ = arena_play(
        env, random_policy, B, args.max_moves, args.seed
    )
    # Both policies start from the SAME reset keys, and hand draws
    # depend only on the step index (the key chain splits every step
    # regardless of action), so game i sees the same shape sequence
    # under both policies: the comparison is PAIRED, which strips the
    # hand-luck variance that dominates this game.
    diffs = scores - r_scores
    report = {
        "source": source,
        "games": B,
        "sims": args.sims,
        "mcts_mean_score": round(float(scores.mean()), 2),
        "mcts_max_score": round(float(scores.max()), 2),
        "mcts_mean_length": round(float(lengths.mean()), 1),
        "finished_fraction": round(float(done.mean()), 3),
        "random_mean_score": round(float(r_scores.mean()), 2),
        "score_vs_random": round(
            float(scores.mean() / max(r_scores.mean(), 1e-9)), 3
        ),
        "paired_mean_diff": round(float(diffs.mean()), 3),
        "paired_win_rate": round(
            float((diffs > 0).mean() + 0.5 * (diffs == 0).mean()), 3
        ),
    }

    # Head-to-head: a second checkpoint plays the SAME paired hands.
    if args.vs_checkpoint or args.vs_run:
        from .config.run_configs import load_run_configs

        model_cfg_b = None
        if args.vs_run:
            cfg_dir_b = run_base_dir(args.vs_run)
        else:
            cfg_dir_b = Path(args.vs_checkpoint).resolve().parent.parent
        loaded_b = load_run_configs(cfg_dir_b)
        if loaded_b:
            env_b, model_cfg_b = loaded_b["env"], loaded_b["model"]
            if env_b != env_cfg:
                raise SystemExit(
                    "Head-to-head needs both runs on the same env "
                    "config; the --vs side trained on a different "
                    "board."
                )
        net_b, source_b = restore_net(
            args.vs_checkpoint, args.vs_run, model_cfg_b
        )
        mcts_b = build_search(net_b, model_cfg_b)
        b_scores, _, _ = serve_play(net_b, mcts_b)
        h2h = scores - b_scores
        report.update(
            {
                "vs_source": source_b,
                "vs_mean_score": round(float(b_scores.mean()), 2),
                "h2h_paired_mean_diff": round(float(h2h.mean()), 3),
                "h2h_win_rate": round(
                    float((h2h > 0).mean() + 0.5 * (h2h == 0).mean()), 3
                ),
            }
        )

    print(_json.dumps(report))
    return 0


def cmd_play(args: argparse.Namespace) -> int:
    """Interactive text play (reference `trianglengin play/debug` CLI,
    its README.md:199-205). Prefers the native C++ engine (instant
    startup); falls back to the jitted JAX engine."""
    import numpy as np

    from .utils.helpers import enforce_platform

    # Interactive play is host-side work; never wake the accelerator
    # (whose init can hang on a sick chip) just to render a board.
    enforce_platform("cpu")

    from .config import EnvConfig
    from .env.engine import TriangleEnv
    from .env.native import native_available, native_build_error
    from .env.render import render_grid, render_shape
    from .env.shapes import bank_shape_triangles

    env_cfg = EnvConfig()
    env = TriangleEnv(env_cfg)
    use_native = args.engine == "native" or (
        args.engine == "auto" and native_available()
    )
    if args.engine == "native" and not native_available():
        print(f"native engine unavailable: {native_build_error()}")
        return 1

    if use_native:
        from .env.native import NativeTriangleEnv

        native = NativeTriangleEnv(env)
        batch = native.new_batch(1, seed=args.seed)

        def state_view():
            return (
                env.unpack_grid_np(batch.occupied[0]),
                batch.shape_idx[0],
                float(batch.score[0]),
                bool(batch.done[0]),
            )

        def do_step(action):
            rewards, _ = native.step(
                batch, np.asarray([action], np.int32)
            )
            return float(rewards[0])

        def valid_mask():
            return native.valid_mask(batch)[0]

    else:
        from .env.game_state import GameState

        game = GameState(env_cfg, initial_seed=args.seed)

        def state_view():
            grid = game.get_grid_data_np()
            hand = [
                -1 if s is None else 0 for s in game.get_shapes()
            ]  # display only
            return (
                grid["occupied"],
                np.asarray(
                    [
                        -1 if s is None else i
                        for i, s in enumerate(game.get_shapes())
                    ]
                ),
                game.game_score(),
                game.is_over(),
            )

        def do_step(action):
            reward, _ = game.step(action)
            return reward

        def valid_mask():
            mask = np.zeros(env_cfg.action_dim, dtype=bool)
            mask[game.valid_actions()] = True
            return mask

    death = env.geometry.death
    cells = env_cfg.ROWS * env_cfg.COLS
    moves = 0
    script = list(args.script.split(";")) if args.script else None
    print(
        f"Board {env_cfg.ROWS}x{env_cfg.COLS}, "
        f"{env_cfg.NUM_SHAPE_SLOTS} shape slots, engine="
        f"{'native' if use_native else 'jax'}."
    )
    print("Moves: 'SLOT ROW COL' | 'v' valid count | 'q' quit.")
    while True:
        occ, hand, score, done = state_view()
        print()
        print(render_grid(occ, death))
        print(f"score={score:.1f}  moves={moves}")
        for slot in range(env_cfg.NUM_SHAPE_SLOTS):
            sidx = int(hand[slot])
            if use_native:
                label = (
                    "(consumed)"
                    if sidx < 0
                    else "\n".join(
                        "    " + line
                        for line in render_shape(
                            bank_shape_triangles(env.bank, sidx)
                        ).splitlines()
                    )
                )
            else:
                shapes = game.get_shapes()
                label = (
                    "(consumed)"
                    if shapes[slot] is None
                    else "\n".join(
                        "    " + line
                        for line in render_shape(
                            shapes[slot].triangles
                        ).splitlines()
                    )
                )
            print(f"  slot {slot}:")
            print(label)
        if done:
            print("GAME OVER.")
            return 0
        if script is not None:
            if not script:
                return 0
            line = script.pop(0).strip()
            print(f"> {line}")
        else:
            try:
                line = input("> ").strip()
            except EOFError:
                return 0
        if line in ("q", "quit", "exit"):
            return 0
        if line == "v":
            print(f"valid placements: {int(valid_mask().sum())}")
            continue
        try:
            slot, r, c = (int(x) for x in line.split())
            action = slot * cells + r * env_cfg.COLS + c
        except ValueError:
            print("Expected: SLOT ROW COL")
            continue
        if not 0 <= action < env_cfg.action_dim:
            print("Out of range.")
            continue
        if not valid_mask()[action]:
            print("Invalid placement (would forfeit); pick another.")
            continue
        reward = do_step(action)
        moves += 1
        print(f"reward {reward:+.1f}")


_BENCH_TARGETS = ("auto", "smoke", "cpu", "1", "2", "3", "4", "5")


def _apply_bench_target(target: "str | None", environ: dict) -> None:
    """Map a warm/fit/tune target onto the bench-plan env knobs:
    digits 1..5 select a BASELINE preset (BENCH_CONFIG), a path to a
    `cli tune` artifact selects the tuned shapes (BENCH_TUNED_PRESET);
    auto/smoke/cpu leave the ambient BENCH_* knobs in charge."""
    if not target or target in ("auto", "smoke", "cpu"):
        return
    if target.isdigit():
        environ["BENCH_CONFIG"] = target
        return
    if Path(target).is_file():
        environ["BENCH_TUNED_PRESET"] = target
        return
    raise SystemExit(
        f"Unknown target {target!r}: expected one of "
        f"{'|'.join(_BENCH_TARGETS)} or a tuned_preset.json path "
        "(emitted by `cli tune`)."
    )


def cmd_warm(args: argparse.Namespace) -> int:
    """AOT-precompile the hot bench/training programs for a preset so a
    later bench/run starts measuring in seconds instead of burning its
    healthy chip window on first-chunk compiles (docs/COMPILE_CACHE.md).

    `benchmarks/tpu_watch.sh` runs this after every successful chip
    probe; by the time a window opens the persistent + AOT executable
    caches already hold the sweep's exact shapes. Exit 0 when every
    requested program is AOT-ready, 1 when any fell back or failed.
    """
    import json as _json
    import os as _os

    from .utils.helpers import enforce_platform

    # `warm cpu` pins the CPU backend (warming the bench's CPU-fallback
    # shapes without waking a possibly-wedged accelerator).
    device = args.device or ("cpu" if args.target == "cpu" else "auto")
    enforce_platform(device)

    import jax

    from .bench_config import resolve_bench_plan
    from .utils.helpers import enable_persistent_compilation_cache
    from .warm import warm_bench_programs

    backend = jax.default_backend()
    # Backend resolved: gate the XLA persistent cache correctly (the
    # AOT executable cache works on every backend regardless).
    enable_persistent_compilation_cache(backend=backend)

    environ = dict(_os.environ)
    smoke = args.target == "smoke" or environ.get("BENCH_SMOKE") == "1"
    # target auto/cpu/smoke: honor ambient BENCH_* knobs as bench does;
    # digits select a BASELINE preset, a path selects a tuned preset.
    _apply_bench_target(args.target, environ)
    plan = resolve_bench_plan(smoke, backend, environ=environ)
    programs = set(args.programs.split(",")) if args.programs else None
    report = warm_bench_programs(
        plan,
        jobs=args.jobs,
        programs=programs,
        progress=lambda msg: print(msg, file=sys.stderr, flush=True),
    )
    print(_json.dumps(report))
    # "skipped-cpu" rows are deliberate (learner programs never AOT on
    # the CPU backend; rl/trainer.py cpu_aot note) — they must not fail
    # the warm, but at least one program must actually be AOT-ready.
    rows = report["programs"]
    ok = all(r["status"] in ("aot", "skipped-cpu") for r in rows)
    return 0 if (ok and any(r["status"] == "aot" for r in rows)) else 1


def cmd_serve(args: argparse.Namespace) -> int:
    """Policy-serving front end (docs/SERVING.md): a continuous-batching
    inference service over the lockstep wave search. Many concurrent
    game sessions multiplex onto ONE compiled `serve/b<B>` search shape
    (serving/service.py); sessions admit/retire between dispatches and
    partial batches pad with frozen lanes, so fluctuating load never
    recompiles.

    Startup composes the training plumbing the ROADMAP names: AOT warm
    start through the compile cache (~0.5s when `cli warm` ran first),
    a `cli fit`-style OOM pre-flight from the serve program's AOT
    memory analysis (exit 1 when over budget — refuse to serve rather
    than OOM a shared chip), then a `health.json` heartbeat + stall
    watchdog and per-request latency records into the metrics ledger
    (`cli perf` summarizes p50/p95 per-move latency; `cli compare`
    gates the SLO).

    Traffic is the built-in simulated-session generator (`--smoke` for
    the bounded CI variant); a network transport plugs in at
    `PolicyService.open_session`/`request_move`/`dispatch`. With
    `--run-name`, `--reload-every` polls the run's checkpoints and
    hot-swaps weights between dispatches without recompiling.
    """
    import json as _json
    import os as _os
    import time as _time

    from .utils.helpers import enforce_platform

    enforce_platform(args.device or ("cpu" if args.smoke else "auto"))

    import jax

    from .utils.helpers import enable_persistent_compilation_cache

    enable_persistent_compilation_cache(backend=jax.default_backend())

    from .config import (
        AlphaTriangleMCTSConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from .config.run_configs import load_run_configs_or_default
    from .env.engine import TriangleEnv
    from .features.core import get_feature_extractor
    from .mcts import BatchedMCTS, GumbelMCTS
    from .nn.network import NeuralNetwork
    from .serving import (
        PolicyService,
        build_serve_telemetry,
        run_simulated_load,
    )
    from .stats.persistence import CheckpointManager
    from .telemetry.health import device_memory_stats
    from .telemetry.memory import (
        BYTES_LIMIT_ENV,
        FIT_OVER,
        fit_verdict,
        fmt_bytes,
        serve_budget_bytes,
    )

    def say(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    def persistence_for(run_name: str) -> "PersistenceConfig":
        p = PersistenceConfig(RUN_NAME=run_name)
        if args.root_dir:
            p = p.model_copy(update={"ROOT_DATA_DIR": args.root_dir})
        return p

    # Board/net configs: the served run's own configs.json when
    # available (the same resolution `cli eval` uses), flagship
    # defaults otherwise.
    if args.run_name:
        cfg_dir = persistence_for(args.run_name).get_run_base_dir()
    elif args.checkpoint:
        cfg_dir = Path(args.checkpoint).resolve().parent.parent
    else:
        cfg_dir = Path("/nonexistent")
    env_cfg, model_cfg = load_run_configs_or_default(cfg_dir)
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=args.sims)
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)

    # Restore weights (optional — an untrained net still serves, which
    # is what the smoke uses).
    trainer = mgr = None
    source = "untrained"
    if args.checkpoint or args.run_name:
        from .rl import Trainer

        trainer = Trainer(net, TrainConfig(RUN_NAME=args.run_name or "serve"))
        mgr = CheckpointManager(persistence_for(args.run_name or "serve"))
        loaded = (
            mgr.restore_path(args.checkpoint, trainer.state)
            if args.checkpoint
            else mgr.restore(trainer.state)
        )
        if loaded.train_state is None:
            say("serve: no checkpoint found; serving the untrained net")
        else:
            trainer.set_state(loaded.train_state)
            trainer.sync_to_network()
            source = f"step {loaded.global_step}"

    if args.gumbel:
        mcts = GumbelMCTS(
            env, extractor, net.model, mcts_cfg, net.support, exploit=True
        )
    else:
        mcts = BatchedMCTS(env, extractor, net.model, mcts_cfg, net.support)

    serve_run = args.serve_run_name or (
        f"serve_{args.run_name}" if args.run_name else "serve"
    )
    run_dir = persistence_for(serve_run).get_run_base_dir()
    telemetry = build_serve_telemetry(
        run_dir, serve_run, env_cfg, model_cfg
    )
    from .compile_cache import get_compile_cache

    get_compile_cache().set_tracer(telemetry.tracer)
    service = PolicyService(
        env,
        extractor,
        net,
        mcts,
        slots=args.slots,
        use_gumbel=args.gumbel,
        telemetry=telemetry,
        rng_seed=args.seed,
        ladder=args.buckets,
    )
    ladder_note = (
        f", ladder {','.join(str(r) for r in service.ladder.rungs)}"
        if args.buckets
        else ""
    )
    say(
        f"serve: {source} net, board {env_cfg.ROWS}x{env_cfg.COLS}, "
        f"{args.slots} slots{ladder_note}, {args.sims} sims/move, "
        f"precision {model_cfg.INFERENCE_PRECISION}, run dir {run_dir}"
    )

    # AOT warm start: deserialize (or compile+serialize) the serve
    # search BEFORE admitting traffic — a `cli warm`-ed cache makes
    # this the ~0.5s path (docs/COMPILE_CACHE.md).
    if not args.no_warm:
        t0 = _time.time()
        aot = service.warm()
        say(
            f"serve: warm {'aot' if aot else 'jit-fallback'} "
            f"({_time.time() - t0:.1f}s)"
        )

    # OOM pre-flight (docs/OBSERVABILITY.md "Memory"): the serve
    # program's resident arguments + dispatch transient vs the device
    # limit — answered before a session is admitted.
    if not args.no_preflight:
        # Pre-flight EVERY ladder rung (a fixed-shape service is a
        # one-rung ladder): the micro-batcher may dispatch any of
        # them mid-stream, so the gate is the worst rung's budget.
        record, budget = None, 0
        for rung in service.ladder.rungs:
            rec = service.analyze(persist=True, rung=rung)
            b = serve_budget_bytes(rec)
            if rec is not None and b >= budget:
                record, budget = rec, b
        limit = None
        override = (args.limit_gb, _os.environ.get(BYTES_LIMIT_ENV, "").strip())
        if override[0] is not None:
            limit = override[0] * 2**30
        elif override[1]:
            try:
                limit = float(override[1])
            except ValueError:
                pass
        if limit is None:
            limits = [
                m.get("bytes_limit")
                for m in device_memory_stats()
                if isinstance(m.get("bytes_limit"), (int, float))
                and m.get("bytes_limit") > 0
            ]
            limit = min(limits) if limits else None
        if budget > 0:
            code, reason = fit_verdict(budget, limit)
            say(f"serve: pre-flight {fmt_bytes(budget)} — {reason}")
            if code == FIT_OVER:
                say("serve: refusing to serve an over-budget config")
                return 1
        else:
            say("serve: pre-flight skipped (no memory analysis available)")
        telemetry.record_memory(record)

    # Hot weight reload: poll the served run's checkpoints between
    # dispatches; a new step restores + swaps variables with zero
    # recompiles (the compiled search reads variables as an input).
    reload_state = {"step": mgr.latest_step() if mgr else None}

    def reload_hook(svc, dispatches: int) -> None:
        if (
            mgr is None
            or trainer is None
            or args.reload_every <= 0
            or dispatches % args.reload_every
        ):
            return
        latest = mgr.latest_step()
        if latest is None or latest == reload_state["step"]:
            return
        loaded = mgr.restore(trainer.state)
        if loaded.train_state is None:
            return
        trainer.set_state(loaded.train_state)
        trainer.sync_to_network()
        reload_state["step"] = latest
        svc.reload_weights()
        say(f"serve: hot-reloaded weights at checkpoint step {latest}")

    telemetry.start()
    waves = []
    try:
        deadline = (
            None
            if args.duration is None
            else _time.monotonic() + args.duration
        )
        while True:
            stats = run_simulated_load(
                service,
                total_sessions=args.sessions,
                # Under a ladder, demand may exceed the base rung —
                # that sustained pressure is what drives the
                # micro-batcher's walk-up (loadgen clamps to the
                # ladder's top rung).
                concurrency=(
                    service.max_slots if args.buckets else args.slots
                ),
                max_moves=args.max_moves,
                seed=args.seed + len(waves),
                tick_every=args.tick_every,
                reload_hook=reload_hook,
                progress=say,
            )
            waves.append(stats)
            if args.smoke or deadline is None:
                break
            if _time.monotonic() >= deadline:
                break
    except KeyboardInterrupt:
        say("serve: interrupted; draining")
    finally:
        service.tick()
        telemetry.close(step=service.dispatch_count)

    report = {
        "run": serve_run,
        "source": source,
        "slots": args.slots,
        "buckets": list(service.ladder.rungs),
        "precision": model_cfg.INFERENCE_PRECISION,
        "rung_switches": service.rung_switches,
        "sims": args.sims,
        "waves": len(waves),
        "sessions_served": sum(w["sessions_served"] for w in waves),
        "moves_served": sum(w["moves_served"] for w in waves),
        "dispatches": service.dispatch_count,
        "weight_reloads": service.weight_reloads,
        "ledger": str(run_dir / "metrics.jsonl"),
        **service.serve_stats(drain=False),
    }
    print(_json.dumps(report))
    # The smoke gate: sessions actually served, latency records on the
    # ledger (`make serve-smoke` then runs cli perf/compare on top).
    if args.smoke:
        ok = report["sessions_served"] >= args.sessions and (
            run_dir / "metrics.jsonl"
        ).exists()
        return 0 if ok else 1
    return 0


def cmd_fleet(args: argparse.Namespace) -> int:
    """Fault-tolerant serve fleet (docs/SERVING.md "Fleet"): N
    PolicyService replica subprocesses behind a least-queue-depth
    router with health-gated admission, per-request timeout + retry
    onto a different replica, optional hedging, and bounded-queue load
    shedding. Replica lifecycle reuses the training supervisor's
    machinery — deaths are doctor-classified since spawn, restarted
    with backoff under a restart budget (a serve-family quarantine
    respawns onto a halved bucket), and every lifecycle/routing
    decision lands crash-safe in the run's fleet.jsonl.

    THIS PARENT NEVER IMPORTS JAX — the same contract as `cli
    supervise`/`cli doctor` (benchmarks/fleet_smoke.py pins it with an
    import guard). JAX lives in the replica children
    (`python -m alphatriangle_tpu.serving.replica`), one compiled
    `serve/b<B>` program each.

    Drives a storm of episode requests through the router and prints
    one JSON report line; `--smoke` additionally gates on the
    zero-lost-requests invariant. `--chaos-kill-after N` /
    `--reload-after N` are the smoke's mid-storm chaos/rolling-swap
    triggers.
    """
    import json as _json
    import threading as _threading
    import time as _time

    from .serving.fleet import FleetSupervisor, run_fleet_load
    from .supervise.policy import RecoveryPolicy

    run_dir = _resolve_run_dir(args.run_name, args.root_dir)
    if run_dir is None:
        return 2
    run_dir.mkdir(parents=True, exist_ok=True)

    def policy_factory() -> RecoveryPolicy:
        return RecoveryPolicy(
            max_restarts=args.max_restarts,
            circuit_breaker_deaths=args.circuit_breaker,
            backoff_base_s=args.backoff_base,
            backoff_max_s=args.backoff_max,
            quarantine_after=args.quarantine_after,
        )

    replica_extra = [
        "--health-interval",
        str(args.replica_health_interval),
        "--dispatch-min-deadline",
        str(args.replica_dispatch_min_deadline),
        "--dispatch-first-deadline",
        str(args.replica_dispatch_first_deadline),
        "--dispatch-watchdog-poll",
        str(args.replica_watchdog_poll),
        "--tick-every",
        str(args.tick_every),
    ]
    if args.buckets:
        # Replicas micro-batch on the SAME rung set the supervisor's
        # quarantine walks down (serving/buckets.py — one ladder, two
        # walkers).
        replica_extra += ["--buckets", args.buckets]
    fleet = FleetSupervisor(
        run_dir,
        replicas=args.replicas,
        slots=args.slots,
        sims=args.sims,
        seed=args.seed,
        configs_dir=run_dir,
        ladder=args.buckets,
        replica_extra_argv=replica_extra,
        policy_factory=policy_factory,
        probe_deadline_s=args.probe_deadline,
        poll_s=args.poll,
        spawn_timeout_s=args.spawn_timeout,
    )
    router = fleet.build_router(
        timeout_s=args.timeout,
        retries=args.retries,
        backoff_base_s=args.route_backoff_base,
        backoff_max_s=args.route_backoff_max,
        hedge_after_s=args.hedge_after,
        max_inflight=args.max_queue,
    )

    chaos_lock = _threading.Lock()
    state = {"killed": False, "reload": None}

    def on_complete(n: int) -> None:
        with chaos_lock:
            kill_now = (
                args.chaos_kill_after > 0
                and not state["killed"]
                and n >= args.chaos_kill_after
            )
            if kill_now:
                state["killed"] = True
            reload_now = (
                args.reload_after > 0
                and state["reload"] is None
                and n >= args.reload_after
            )
            if reload_now:
                state["reload"] = _threading.Thread(
                    target=fleet.rolling_reload,
                    name="fleet-reload",
                    daemon=True,
                )
        if kill_now:
            victim = fleet.kill_replica()
            print(f"fleet: chaos-killed {victim}", file=sys.stderr)
        if reload_now:
            state["reload"].start()

    print(
        f"fleet: {args.replicas} replicas x {args.slots} slots, "
        f"{args.requests} requests, run dir {run_dir}",
        file=sys.stderr,
    )
    try:
        fleet.start()
        storm = run_fleet_load(
            router,
            fleet,
            requests=args.requests,
            concurrency=args.concurrency,
            max_moves=args.max_moves,
            seed=args.seed,
            timeout_s=args.timeout,
            on_complete=on_complete,
        )
        if state["reload"] is not None:
            state["reload"].join(timeout=180.0)
        # Let pending respawn chains settle so the death -> verdict ->
        # respawn -> readmit sequence completes on fleet.jsonl before
        # the report (and the smoke's ledger assertions) read it.
        deadline = _time.monotonic() + args.settle
        while _time.monotonic() < deadline:
            if all(
                h.name in fleet.gaveup or h.routable for h in fleet.handles
            ):
                break
            _time.sleep(0.2)
    finally:
        fleet.stop()

    report = {
        "schema": "alphatriangle.fleet.v1",
        "run": args.run_name or run_dir.name,
        "replicas": args.replicas,
        "slots": args.slots,
        **storm,
        "fleet": fleet.summary(),
        "ledger": str(run_dir / "fleet.jsonl"),
    }
    # Aggregated whole-fleet scrape surface + SLO snapshot
    # (telemetry/slo.py): rejection codes as DISTINCT counters, per-SLO
    # burn rates as gauges — written after the storm so one textfile
    # describes the whole run.
    from .telemetry.ledger import read_ledger as _read_ledger
    from .telemetry.perf import summarize_fleet as _summarize_fleet
    from .telemetry.slo import (
        FLEET_PROM_FILENAME,
        evaluate_slos,
        write_fleet_prometheus,
    )

    slo_report = evaluate_slos(run_dir)
    write_fleet_prometheus(
        run_dir / FLEET_PROM_FILENAME,
        _summarize_fleet(_read_ledger(run_dir / "fleet.jsonl")),
        slo_report,
        run_name=args.run_name or run_dir.name,
    )
    report["slo"] = slo_report["status"]
    print(_json.dumps(report))
    if args.smoke:
        accounted = (
            storm["completed"] + storm["shed"] == storm["terminal"]
            and storm["terminal"] == storm["requests"]
        )
        ok = storm["lost"] == 0 and storm["completed"] > 0 and accounted
        return 0 if ok else 1
    return 0


def cmd_league(args: argparse.Namespace) -> int:
    """Experience-flywheel mode (docs/LEAGUE.md): one process runs the
    learner while a `PolicyService` plays matchmade games against a
    league of past checkpoints, the served trajectories flowing into
    the replay ring interleaved with self-play at --mix. The pool is
    seeded from --pool-from's checkpoints; the flywheel run's own
    promotions grow it. Board/net configs come from the pool run's
    configs.json so pool checkpoints actually load.

    Emits one JSON report line (pool size, ratings, promotions,
    ingest) — the `make league-smoke` contract."""
    import json as _json

    from .config import (
        AlphaTriangleMCTSConfig,
        LeagueConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from .config.run_configs import load_run_configs_or_default
    from .league import LEAGUE_FILENAME, LIVE_ID, LeaguePool, run_flywheel

    def persistence_for(run_name: str) -> "PersistenceConfig":
        p = PersistenceConfig(RUN_NAME=run_name)
        if args.root_dir:
            p = p.model_copy(update={"ROOT_DATA_DIR": args.root_dir})
        return p

    overrides: dict = {
        # Auto-resume would redirect RUN_NAME at the newest
        # checkpointed run — typically the --pool-from source itself —
        # and train INTO it. The flywheel names its run explicitly.
        "AUTO_RESUME_LATEST": False,
    }
    if args.run_name is not None:
        overrides["RUN_NAME"] = args.run_name
    if args.seed is not None:
        overrides["RANDOM_SEED"] = args.seed
    if args.steps is not None:
        overrides["MAX_TRAINING_STEPS"] = args.steps
    if args.self_play_batch is not None:
        overrides["SELF_PLAY_BATCH_SIZE"] = args.self_play_batch
    if args.batch_size is not None:
        overrides["BATCH_SIZE"] = args.batch_size
    if args.buffer_capacity is not None:
        overrides["BUFFER_CAPACITY"] = args.buffer_capacity
    if args.min_buffer is not None:
        overrides["MIN_BUFFER_SIZE_TO_TRAIN"] = args.min_buffer
    if args.rollout_chunk is not None:
        overrides["ROLLOUT_CHUNK_MOVES"] = args.rollout_chunk
    if args.checkpoint_freq is not None:
        overrides["CHECKPOINT_SAVE_FREQ_STEPS"] = args.checkpoint_freq
    if args.device_replay is not None:
        overrides["DEVICE_REPLAY"] = args.device_replay
    if args.max_moves is not None:
        overrides["MAX_EPISODE_MOVES"] = args.max_moves
    if args.device is not None:
        overrides["DEVICE"] = args.device
    train_config = TrainConfig(**overrides)

    league_kw: dict = {}
    if args.slots is not None:
        league_kw["LEAGUE_SLOTS"] = args.slots
    if args.games is not None:
        league_kw["GAMES_PER_ROUND"] = args.games
    if args.mix is not None:
        league_kw["LEAGUE_MIX_RATIO"] = args.mix
    if args.max_moves is not None:
        league_kw["MAX_GAME_MOVES"] = args.max_moves
    if args.reload_every is not None:
        league_kw["RELOAD_EVERY_STEPS"] = args.reload_every
    if args.staleness_window is not None:
        league_kw["STALENESS_WINDOW"] = args.staleness_window
    if args.promotion_games is not None:
        league_kw["PROMOTION_MIN_GAMES"] = args.promotion_games
    if args.promotion_win_rate is not None:
        league_kw["PROMOTION_WIN_RATE"] = args.promotion_win_rate
    if args.exploration_floor is not None:
        league_kw["EXPLORATION_FLOOR"] = args.exploration_floor
    league_config = LeagueConfig(**league_kw)

    # Board/net configs from the pool source run: the pool's
    # checkpoints must restore into this geometry.
    cfg_dir = persistence_for(args.pool_from).get_run_base_dir()
    env_config, model_config = load_run_configs_or_default(cfg_dir)
    mcts_config = (
        AlphaTriangleMCTSConfig(max_simulations=args.sims)
        if args.sims is not None
        else None
    )

    telemetry_config = None
    if args.no_telemetry:
        from .config import TelemetryConfig

        telemetry_config = TelemetryConfig(ENABLED=False)

    persistence_config = persistence_for(train_config.RUN_NAME)
    code = run_flywheel(
        train_config=train_config,
        league_config=league_config,
        env_config=env_config,
        model_config=model_config,
        mcts_config=mcts_config,
        persistence_config=persistence_config,
        telemetry_config=telemetry_config,
        pool_from=args.pool_from,
        use_tensorboard=False,
    )

    run_dir = persistence_config.get_run_base_dir()
    pool = LeaguePool(run_dir / LEAGUE_FILENAME)
    report = {
        "run": train_config.RUN_NAME,
        "pool_from": args.pool_from,
        "exit": code,
        "pool_size": len(pool),
        "promotions": pool.promotions,
        "live_elo": round(pool.rating(LIVE_ID), 2),
        "ratings": {
            m: round(pool.rating(m), 2) for m in pool.member_ids()
        },
        "league_jsonl": str(run_dir / LEAGUE_FILENAME),
        "ledger": str(run_dir / "metrics.jsonl"),
    }
    print(_json.dumps(report))
    return code


def cmd_fit(args: argparse.Namespace) -> int:
    """OOM pre-flight gate (docs/OBSERVABILITY.md "Memory"): compose
    the static per-device memory budget for a bench/preset scale —
    train-state tree bytes + replay-ring bytes + AOT-analyzed program
    memory (`compiled.memory_analysis()`, never executed) — and check
    it against the device byte limit BEFORE a scarce accelerator
    window is burned on an OOM. Exit 0 = fits, 1 = over budget, 2 =
    no device limit known (set ALPHATRIANGLE_DEVICE_BYTES_LIMIT or
    --limit-gb to assert one)."""
    import json as _json
    import os as _os

    from .utils.helpers import enforce_platform

    device = args.device or ("cpu" if args.target == "cpu" else "auto")
    enforce_platform(device)

    import jax

    from .bench_config import resolve_bench_plan
    from .telemetry.memory import (
        estimate_fit,
        fit_verdict,
        fmt_bytes,
        resolve_bytes_limit,
    )
    from .utils.helpers import enable_persistent_compilation_cache

    backend = jax.default_backend()
    enable_persistent_compilation_cache(backend=backend)
    environ = dict(_os.environ)
    smoke = args.target == "smoke" or environ.get("BENCH_SMOKE") == "1"
    _apply_bench_target(args.target, environ)
    plan = resolve_bench_plan(smoke, backend, environ=environ)
    print(
        f"fit: backend={backend} scale={plan.scale} batch={plan.sp_batch} "
        f"chunk={plan.chunk} lbatch={plan.lbatch} "
        f"device_replay={plan.device_replay}",
        file=sys.stderr,
        flush=True,
    )
    report = estimate_fit(
        plan.env,
        plan.model,
        plan.mcts,
        plan.train,
        fused_k=plan.fused_k,
        device_replay=plan.device_replay,
        # Bench-plan ring capacities are small (10k rows), so the
        # megastep program — whose argument list includes the ring —
        # is analyzed here too (rl/megastep.py).
        megastep=True,
        # --serve additionally analyzes the policy service's
        # `serve/b<B>` search program and persists its .mem.json
        # sidecar (serving/service.py; docs/SERVING.md).
        serve=args.serve,
        serve_batch=plan.serve_batch,
        # Every ladder rung is analyzed (BENCH_SERVE_BUCKETS /
        # serving/buckets.py): the micro-batcher can dispatch any of
        # them, so the budget covers the whole rung set.
        serve_buckets=plan.serve_buckets,
        progress=lambda msg: print(msg, file=sys.stderr, flush=True),
    )
    budget = report["budget"]
    # Per-device byte limit: explicit flag wins, then the env override,
    # then the smallest limit any local device reports (conservative).
    limit, source = resolve_bytes_limit(args.limit_gb, environ)
    code, reason = fit_verdict(budget["total_bytes"], limit)
    if args.json:
        print(
            _json.dumps(
                {
                    "schema": "alphatriangle.fit.v1",
                    "scale": plan.scale,
                    "backend": backend,
                    "budget": budget,
                    "bytes_limit": limit,
                    "limit_source": source,
                    "exit": code,
                    "reason": reason,
                    "records": report["records"],
                }
            )
        )
        return code
    print(f"fit {plan.scale} on {backend}")
    for label, key in (
        ("train state", "train_state_bytes"),
        ("replay ring (device)", "replay_ring_bytes"),
        ("rollout residency", "rollout_resident_bytes"),
        ("program transient", "program_transient_bytes"),
    ):
        print(f"  {label:<22} {fmt_bytes(budget[key]):>12}")
    print(f"  {'TOTAL (per device)':<22} {fmt_bytes(budget['total_bytes']):>12}")
    print(
        f"  limit                  {fmt_bytes(limit):>12}"
        + (f"  [{source}]" if limit is not None else "")
    )
    print(reason)
    return code


def cmd_mem(args: argparse.Namespace) -> int:
    """Memory-attribution table for a run, rendered from its artifacts
    alone (`metrics.jsonl` `kind: "memory"` + `"util"` records) —
    never imports JAX, safe beside a wedged chip. Exit 0 on a usable
    table, 2 when the run has no memory records (predates the memory
    ledger, or telemetry was disabled)."""
    import json as _json

    from .telemetry.ledger import read_ledger, resolve_ledger_path
    from .telemetry.memory import (
        attribution_rows,
        compose_budget,
        fmt_bytes,
    )

    target = Path(args.run) if args.run else None
    if target is not None and target.exists():
        ledger = resolve_ledger_path(target)
    else:
        run_dir = _resolve_run_dir(args.run, args.root_dir)
        if run_dir is None:
            return 2
        ledger = resolve_ledger_path(run_dir)
    if ledger is None:
        print(f"no metrics ledger for {args.run}", file=sys.stderr)
        return 2
    records = read_ledger(ledger, kinds={"memory"})
    utils = read_ledger(ledger, kinds={"util"})
    observed = next(
        (
            u
            for u in reversed(utils)
            if isinstance(u.get("mem_bytes_in_use"), (int, float))
        ),
        None,
    )
    if not records and observed is None:
        print(
            f"{ledger}: no memory records (run predates the memory "
            "ledger, or telemetry was disabled)",
            file=sys.stderr,
        )
        return 2
    budget = compose_budget(records)
    if args.json:
        print(
            _json.dumps(
                {
                    "source": str(ledger),
                    "records": records,
                    "budget": budget,
                    "observed": observed,
                }
            )
        )
        return 0
    print(f"mem {ledger}")
    rows = attribution_rows(records)
    if rows:
        width = max(max(len(r[0]) for r in rows), 9)
        print(f"  {'component':<{width}}  {'bytes':>12}  detail")
        for component, total, detail in rows:
            print(f"  {component:<{width}}  {fmt_bytes(total):>12}  {detail}")
        print(
            f"  static budget (per device): "
            f"{fmt_bytes(budget['total_bytes'])} = "
            f"state {fmt_bytes(budget['train_state_bytes'])}"
            f" + ring {fmt_bytes(budget['replay_ring_bytes'])}"
            f" + rollout {fmt_bytes(budget['rollout_resident_bytes'])}"
            f" + transient {fmt_bytes(budget['program_transient_bytes'])}"
        )
    if observed is not None:
        limit = observed.get("mem_bytes_limit")
        util = observed.get("mem_utilization")
        print(
            f"  observed: {fmt_bytes(observed.get('mem_bytes_in_use'))} "
            f"in use, peak {fmt_bytes(observed.get('mem_peak_bytes_in_use'))}"
            + (
                f", limit {fmt_bytes(limit)}"
                + (f" ({util:.1%} used)" if isinstance(util, (int, float)) else "")
                if limit
                else ""
            )
            + f" (step {observed.get('step')})"
        )
    return 0


def cmd_roofline(args: argparse.Namespace) -> int:
    """Roofline attribution report for a run: per-program arithmetic
    intensity vs the device machine balance (compute- vs memory-bound,
    achieved-vs-roofline fraction) plus chip-idle gap forensics over
    the flight timeline (docs/OBSERVABILITY.md "Roofline & gap
    attribution"). Rendered from run artifacts alone (`metrics.jsonl`
    `kind:"cost"` records, `flight.jsonl`, `trace.json`) — never
    imports JAX, safe beside a wedged chip. Missing/corrupt/legacy
    cost sidecars degrade to "—" cells, never raise. Exit 0 on a
    usable report, 2 when the run has neither cost records nor a
    flight timeline (predates the roofline plane, or telemetry was
    disabled)."""
    import json as _json

    from .telemetry.flight import FLIGHT_FILENAME, read_flight
    from .telemetry.ledger import read_ledger, resolve_ledger_path
    from .telemetry.perf import summarize_utilization
    from .telemetry.roofline import summarize_roofline

    target = Path(args.run) if args.run else None
    if target is not None and target.exists():
        ledger = resolve_ledger_path(target)
    else:
        run_dir = _resolve_run_dir(args.run, args.root_dir)
        if run_dir is None:
            return 2
        ledger = resolve_ledger_path(run_dir)
    if ledger is None:
        print(f"no metrics ledger for {args.run}", file=sys.stderr)
        return 2
    run_dir = ledger.parent
    records = read_ledger(ledger)
    # Device identity + peak FLOP/s from the same summary `cli perf`
    # renders (the writer stamped them onto util records).
    util = summarize_utilization(records) or {}
    summary = summarize_roofline(
        [r for r in records if r.get("kind") == "cost"],
        read_flight(run_dir / FLIGHT_FILENAME),
        device_kind=util.get("device_kind") or "",
        peak_tflops=util.get("peak_bf16_tflops"),
        trace_path=run_dir / "trace.json",
    )
    if summary is None:
        print(
            f"{run_dir}: no cost records or flight timeline (run "
            "predates the roofline plane, or telemetry was disabled)",
            file=sys.stderr,
        )
        return 2
    if args.json:
        summary["source"] = str(ledger)
        print(_json.dumps(summary))
        return 0
    peak = summary.get("peak_bf16_tflops")
    hbm = summary.get("peak_hbm_gbps")
    print(f"roofline {run_dir}")
    print(
        f"  device       {summary.get('device_kind') or '?'}"
        f"   peak bf16 {_fmt_cell(peak, ',.0f', 1, ' TFLOP/s') if peak else 'unknown'}"
        f"   hbm {_fmt_cell(hbm, ',.0f', 1, ' GB/s') if hbm else 'unknown'}"
        + (
            f" [{summary.get('peak_hbm_source')}]"
            if summary.get("peak_hbm_source") not in (None, "unknown")
            else ""
        )
        + (
            f"   balance {_fmt_cell(summary.get('machine_balance_flops_per_byte'), ',.0f', 1, ' FLOP/B')}"
            if summary.get("machine_balance_flops_per_byte") is not None
            else ""
        )
    )
    attrib = summary.get("attribution")
    if attrib:
        print(
            f"  attribution  wall {_fmt_cell(attrib.get('wall_s'), ',.1f', 1, 's')}"
            f"   dispatch {_fmt_cell(attrib.get('dispatch_s'), ',.1f', 1, 's')}"
            f"   idle {_fmt_cell(attrib.get('chip_idle_fraction'), ',.1f', 100.0, '%')}"
            f"   attributed {_fmt_cell(attrib.get('attributed_fraction'), ',.1f', 100.0, '%')}"
            f"   dispatches {_fmt_cell(attrib.get('dispatches'), ',.0f')}"
        )
        gaps = attrib.get("gaps") or {}
        gap_text = "   ".join(
            f"{cat} {_fmt_cell(s, ',.2f', 1, 's')}"
            for cat, s in gaps.items()
            if isinstance(s, (int, float))
        )
        if gap_text:
            print(f"  gaps         {gap_text}")
    else:
        print("  attribution  — (no flight timeline)")
    programs = summary.get("programs") or []
    if programs:
        width = max(max(len(p["program"]) for p in programs), 7)
        print(
            f"  {'program':<{width}}  {'count':>6}  {'p50':>9}  {'total':>9}"
            f"  {'gflops':>9}  {'intensity':>10}  {'bound':>7}  {'roofline':>8}"
        )
        for p in programs:
            print(
                f"  {p['program']:<{width}}"
                f"  {_fmt_cell(p.get('count'), ',.0f'):>6}"
                f"  {_fmt_cell(p.get('wall_s_p50'), ',.1f', 1e3, 'ms'):>9}"
                f"  {_fmt_cell(p.get('wall_s_total'), ',.1f', 1, 's'):>9}"
                f"  {_fmt_cell(p.get('flops'), ',.2f', 1e-9):>9}"
                f"  {_fmt_cell(p.get('intensity'), ',.1f'):>10}"
                f"  {p.get('bound') or '—':>7}"
                f"  {_fmt_cell(p.get('roofline_fraction'), ',.2f', 100.0, '%'):>8}"
            )
    return 0


def cmd_lint(args: argparse.Namespace) -> int:
    """graftlint: the JAX-hazard static analyzer (docs/ANALYSIS.md).

    Walks the package AST for the six hazard classes this repo has
    actually hit (use-after-donation, host-sync-in-hot-path,
    mixed-placement-dispatch, unbracketed-hot-dispatch, debug-artifact,
    untracked-rng). Never imports JAX — runs in CI images, in the
    tpu_watch.sh preflight, and beside a wedged chip, like `cli mem`
    and `cli doctor` (pinned by an import-guard test).

    Exit 0 clean / 1 findings or stale baseline entries / 2 parse
    error (or unknown --rule)."""
    import json as _json

    from .analysis import run_lint, write_baseline

    root = Path(args.path) if args.path else Path(__file__).resolve().parent
    if not root.exists():
        print(f"lint root {root} does not exist", file=sys.stderr)
        return 2
    if args.baseline is not None:
        baseline = Path(args.baseline)
    else:
        # Checked-in default: lint_baseline.json beside the scanned
        # tree (repo root for the package default), else inside it.
        candidates = [
            root.parent / "lint_baseline.json",
            root / "lint_baseline.json",
        ]
        baseline = next((c for c in candidates if c.exists()), None)
    try:
        report = run_lint(
            root, rule_names=args.rule or None, baseline_path=baseline
        )
    except ValueError as e:  # unknown rule / corrupt baseline
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        target = baseline or root.parent / "lint_baseline.json"
        write_baseline(target, report.findings)
        print(
            f"baseline written: {target} "
            f"({len(report.findings)} entr"
            f"{'y' if len(report.findings) == 1 else 'ies'})"
        )
        return 0
    if args.json:
        payload = report.as_dict()
        payload["baseline_path"] = str(baseline) if baseline else None
        print(_json.dumps(payload))
    else:
        print(report.render())
    return report.exit_code


def cmd_slo(args: argparse.Namespace) -> int:
    """Fleet SLO report (telemetry/slo.py): availability, p95 move
    latency, and dispatch success evaluated as error budgets with
    multi-window burn-rate alerts, purely from records the fleet
    already ledgered. Never imports JAX.

    Exit code IS the alert state: 0 every SLO within budget, 1 at
    least one window burning past its threshold, 2 no data (not a
    fleet run dir, or nothing ledgered yet) — pinned by tests and the
    trace-smoke's healthy/brownout contract."""
    import json as _json

    from .telemetry.slo import (
        FLEET_PROM_FILENAME,
        SLO_EXIT_CODES,
        evaluate_slos,
        slo_status_line,
        write_fleet_prometheus,
    )

    target = Path(args.run) if args.run else None
    if target is not None and target.is_dir():
        run_dir = target
    else:
        run_dir = _resolve_run_dir(args.run, args.root_dir)
        if run_dir is None:
            return SLO_EXIT_CODES["no-data"]
    windows = None
    if args.window:
        try:
            windows = tuple(
                (float(w.split(":")[0]), float(w.split(":")[1]))
                for w in args.window
            )
        except (ValueError, IndexError):
            print(
                f"bad --window {args.window!r}: want SECONDS:BURN "
                "(e.g. 300:14.4)",
                file=sys.stderr,
            )
            return SLO_EXIT_CODES["no-data"]
    kw = {"windows": windows} if windows else {}
    report = evaluate_slos(
        run_dir,
        now=args.now,
        latency_threshold_ms=args.latency_threshold,
        **kw,
    )
    if args.prom:
        from .telemetry.ledger import read_ledger
        from .telemetry.perf import summarize_fleet

        write_fleet_prometheus(
            run_dir / FLEET_PROM_FILENAME,
            summarize_fleet(read_ledger(run_dir / "fleet.jsonl")),
            report,
            run_name=run_dir.name,
        )
    if args.json:
        print(_json.dumps(report))
        return int(report["exit_code"])
    print(f"slo {run_dir}")
    print(f"  {slo_status_line(report)}")
    for slo in report["slos"]:
        print(
            f"  {slo['name']:<18} objective {slo['objective']:.2%}  "
            f"budget {slo['error_budget']:.2%}  [{slo['status']}]"
        )
        for w in slo["windows"]:
            flag = "  BURNING" if w["burning"] else ""
            print(
                f"    window {w['window_s']:>6g}s  "
                f"total {w['total']:>10,.0f}  bad {w['bad']:>8,.0f}  "
                f"err {w['error_rate']:.4f}  "
                f"burn x{w['burn_rate']:,.1f} "
                f"(alert at x{w['burn_threshold']:g}){flag}"
            )
    print(
        f"  status    {report['status']} "
        f"(exit {report['exit_code']})"
    )
    return int(report["exit_code"])


def cmd_doctor(args: argparse.Namespace) -> int:
    """Postmortem window forensics: classify how a run ended from its
    on-disk evidence alone (flight ring + health.json + wedge report +
    metrics ledger). Never imports JAX — safe to run beside (or after)
    a wedged chip, which is the whole point: the chip window that
    produced the artifacts may be unusable.

    Exit code IS the verdict (telemetry/flight.py DOCTOR_EXIT_CODES):
    0 clean, 2 never-started, 3 compile-hung, 4 dispatch-hung,
    5 host-stall, 6 oom, 7 preempted. `benchmarks/tpu_watch.sh` appends
    the verdict to its cumulative windows.jsonl per reclaimed window.
    (Related process exit codes, docs/OBSERVABILITY.md: 113 = dispatch
    watchdog wedge, 114 = preemption absorbed, 115 = `cli supervise`
    gave up.)"""
    import json as _json

    from .telemetry.flight import (
        FLIGHT_FILENAME,
        PREEMPT_REPORT_FILENAME,
        WEDGE_REPORT_FILENAME,
        classify_run,
        read_flight,
        read_preempt_report,
        read_wedge_report,
    )
    from .telemetry.health import read_health
    from .telemetry.ledger import read_ledger, resolve_ledger_path

    target = Path(args.run) if args.run else None
    if target is not None and target.exists():
        run_dir = target if target.is_dir() else target.parent
    else:
        run_dir = _resolve_run_dir(args.run, args.root_dir)
        if run_dir is None:
            return 2
    if (run_dir / "fleet.jsonl").exists():
        # Fleet-parent run dir: no learner heartbeat, no device
        # dispatches of its own — classify_run would misread it as
        # never-started. Classify from the fleet ledger + per-replica
        # death verdicts instead (serving/fleet.py classify_fleet).
        from .serving.fleet import classify_fleet

        verdict = classify_fleet(run_dir)
        if args.json:
            verdict["run_dir"] = str(run_dir)
            print(_json.dumps(verdict))
            return int(verdict["exit_code"])
        ev = verdict["evidence"]
        print(f"doctor {run_dir} (fleet parent)")
        print(
            f"  verdict   {verdict['verdict']}"
            + (
                f"  ({verdict['program']} [{verdict['family']}])"
                if verdict.get("program")
                else ""
            )
        )
        if verdict.get("detail"):
            print(f"  detail    {verdict['detail']}")
        print(
            f"  evidence  {ev['fleet_events']} fleet events, "
            f"{ev['deaths']} deaths, {ev['respawns']} respawns, "
            f"{ev['evictions']} evictions, {len(ev['gaveup'])} gave up"
            + (", fleet-stop" if ev["fleet_stop"] else ", NO fleet-stop")
            + (", storm summary" if ev.get("storm_summary") else "")
            + (
                f", {ev['unsealed_route_intents']} unsealed route "
                "intent(s)"
                if ev.get("unsealed_route_intents")
                else ""
            )
        )
        return int(verdict["exit_code"])
    flight = read_flight(run_dir / FLIGHT_FILENAME)
    health = read_health(run_dir / "health.json")
    wedge = read_wedge_report(run_dir / WEDGE_REPORT_FILENAME)
    preempt = read_preempt_report(run_dir / PREEMPT_REPORT_FILENAME)
    ledger = resolve_ledger_path(run_dir)
    utils = read_ledger(ledger, kinds={"util"}) if ledger else []
    # Progress-beacon forensics (telemetry/device_stats.py): the newest
    # beacons.jsonl row names the phase a hung program last announced.
    # Missing file (legacy run / never armed) -> None, zero new output.
    from .telemetry.device_stats import describe_beacon, last_beacon

    beacon = last_beacon(run_dir)
    verdict = classify_run(
        flight,
        health=health,
        utils=utils,
        wedge=wedge,
        preempt=preempt,
        beacon=beacon,
    )
    if args.json:
        verdict["run_dir"] = str(run_dir)
        print(_json.dumps(verdict))
        return int(verdict["exit_code"])
    ev = verdict["evidence"]
    print(f"doctor {run_dir}")
    print(
        f"  verdict   {verdict['verdict']}"
        + (
            f"  ({verdict['program']} [{verdict['family']}])"
            if verdict.get("program")
            else ""
        )
    )
    if verdict.get("detail"):
        print(f"  detail    {verdict['detail']}")
    if verdict.get("last_beacon"):
        print(f"  beacon    {describe_beacon(verdict['last_beacon'])}")
    print(
        f"  evidence  {ev['intents']} intents, {ev['seals']} seals, "
        f"{ev['unsealed']} unsealed"
        + (", wedge report" if ev["wedge_report"] else "")
        + (", preempt report" if ev.get("preempt_report") else "")
        + (", stalled heartbeat" if ev["stalled"] else "")
        + (
            f", mem {ev['mem_utilization']:.0%}"
            if isinstance(ev.get("mem_utilization"), float)
            else ""
        )
    )
    return int(verdict["exit_code"])


def cmd_supervise(args: argparse.Namespace) -> int:
    """Self-healing parent for `cli train` / `cli league`: spawn the
    child, classify every death with the doctor's evidence, and apply
    the verdict->action matrix (restart from the latest committed
    checkpoint with backoff, degrade on OOM, quarantine a repeatedly
    wedging program family, give up past the restart budget). JAX-free
    like `cli doctor` — the parent outlives a wedged chip.

    Exits 0 when the child completes, 115 when the policy gives up,
    or the child's own code after a forwarded SIGTERM/SIGINT (114 for
    an absorbed preemption). Events land in runs/<run>/supervisor.jsonl
    (docs/ROBUSTNESS.md)."""
    from .supervise import RecoveryPolicy, Supervisor

    child = list(args.child or [])
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        child = ["train"]
    if child[0] in ("train", "league"):
        # Pin the child to the supervised run dir: the restarted child
        # must resume ITS run, not auto-resume-redirect to whichever
        # run dir is newest, and train/league both restore from their
        # named run's latest valid checkpoint unconditionally.
        if "--run-name" not in child:
            child += ["--run-name", args.run_name]
        if args.root_dir and "--root-dir" not in child:
            child += ["--root-dir", args.root_dir]
        if child[0] == "train" and "--no-auto-resume" not in child:
            child.append("--no-auto-resume")
    run_dir = _resolve_run_dir(args.run_name, args.root_dir)
    if run_dir is None:
        return 2
    policy = RecoveryPolicy(
        max_restarts=args.max_restarts,
        circuit_breaker_deaths=args.circuit_breaker,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        quarantine_after=args.quarantine_after,
    )
    argv = [sys.executable, "-m", "alphatriangle_tpu.cli", *child]
    print(f"supervise: {run_dir}\n  child: {' '.join(child)}")
    return Supervisor(argv, run_dir, policy=policy).run()


def _tune_axes(
    scale: str, plan, smoke: bool, device_count: int
) -> "tuple[list, list, list, list, list]":
    """Default (batches, capacities, chunks, fused_ks, dps) per scale.

    Grids bracket the scale's plan shapes: the point of the search is
    to discover how much LARGER than the hand-picked config the chip
    can actually go, so each axis extends above the plan value. Smoke
    keeps the lattice tiny — `make tune-smoke` pays a couple of
    estimate_fit compiles, not a sweep."""
    b0 = plan.sp_batch
    cap0 = plan.train.BUFFER_CAPACITY
    t0 = plan.chunk
    k0 = plan.fused_k
    if smoke:
        batches = [max(4, b0 // 2), b0]
        capacities = [cap0]
        chunks = [t0]
        fused_ks = [k0]
    elif scale == "cpu":
        batches = [b0 // 2, b0, b0 * 2]
        capacities = [cap0, cap0 * 2]
        chunks = [t0, t0 * 2]
        fused_ks = [k0]
    else:
        batches = [b0 // 2, b0, b0 * 2, b0 * 4]
        capacities = [cap0, cap0 * 5, cap0 * 10]
        chunks = [t0, t0 * 2]
        fused_ks = [k0, k0 * 2]
    dps = [1]
    if device_count > 1 and not smoke:
        dps.append(device_count)
    return batches, capacities, chunks, fused_ks, dps


def cmd_tune(args: argparse.Namespace) -> int:
    """Fit-driven offline autotuner (docs/AUTOTUNE.md).

    Searches the (SELF_PLAY_BATCH_SIZE, BUFFER_CAPACITY, chunk T,
    fused K, dp, geometry) space for the feasible config maximizing
    PREDICTED games/hour — feasibility from `estimate_fit`'s AOT
    memory analysis (programs are compiled, never executed; no chip
    window is burned), the objective from the analytic FLOPs model
    calibrated against ledger history (`--calibrate`). Emits
    `runs/<run>/tuned_preset.json`, consumable by `cli train --preset`,
    `cli warm`, `cli fit` and `bench.py` (BENCH_TUNED_PRESET).

    Exit 0: winner found + artifact written. Exit 1: no feasible
    candidate under the limit. Exit 2: no device byte limit known
    (set --limit-gb or ALPHATRIANGLE_DEVICE_BYTES_LIMIT).
    """
    import json as _json
    import os as _os

    from .utils.helpers import enforce_platform

    device = args.device or ("cpu" if args.target == "cpu" else "auto")
    enforce_platform(device)

    import jax

    from .autotune import (
        SearchSpace,
        build_tuned_preset,
        calibration_from_targets,
        default_artifact_path,
        run_search,
        write_tuned_preset,
    )
    from .bench_config import resolve_bench_plan
    from .telemetry.memory import (
        FIT_OVER,
        FIT_UNKNOWN,
        fmt_bytes,
        resolve_bytes_limit,
    )
    from .utils.flops import peak_bf16_tflops_info
    from .utils.helpers import enable_persistent_compilation_cache

    backend = jax.default_backend()
    enable_persistent_compilation_cache(backend=backend)
    environ = dict(_os.environ)
    smoke = (
        args.target == "smoke"
        or args.smoke
        or environ.get("BENCH_SMOKE") == "1"
    )
    _apply_bench_target(args.target, environ)
    plan = resolve_bench_plan(smoke, backend, environ=environ)

    limit, limit_source = resolve_bytes_limit(args.limit_gb, environ)
    if limit is None:
        print(
            "tune: no per-device byte limit known — pass --limit-gb or "
            "set ALPHATRIANGLE_DEVICE_BYTES_LIMIT (a search without a "
            "memory budget has no feasibility oracle).",
            file=sys.stderr,
        )
        return FIT_UNKNOWN

    device_kind = jax.devices()[0].device_kind
    peak, peak_source = peak_bf16_tflops_info(device_kind)
    device_count = jax.device_count()

    # Loop mode being tuned: the fused megastep when the plan would run
    # it (device ring available), else the sync loop. CPU/smoke tunes
    # sync — the megastep still dispatches on CPU but its learner
    # programs cannot AOT there (rl/trainer.py cpu_aot).
    mode = args.mode
    if mode == "auto":
        mode = "megastep" if plan.device_replay else "sync"

    batches, capacities, chunks, fused_ks, dps = _tune_axes(
        plan.scale, plan, smoke, device_count
    )
    if args.batches:
        batches = [int(v) for v in args.batches.split(",")]
    if args.capacities:
        capacities = [int(v) for v in args.capacities.split(",")]
    if args.chunks:
        chunks = [int(v) for v in args.chunks.split(",")]
    if args.fused_k:
        fused_ks = [int(v) for v in args.fused_k.split(",")]
    if args.dp:
        dps = [int(v) for v in args.dp.split(",")]
    geometries = (
        args.geometries.split(",") if args.geometries else ["plan"]
    )
    kernel_backends = (
        args.kernel_backends.split(",")
        if getattr(args, "kernel_backends", None)
        else ["xla"]
    )
    precisions = (
        args.precisions.split(",")
        if getattr(args, "precisions", None)
        else ["float32"]
    )
    tree_reuses = (
        [v.strip() == "on" for v in args.tree_reuse.split(",")]
        if getattr(args, "tree_reuse", None)
        else [False]
    )
    serve_ladders = (
        ["" if v.strip() in ("off", "") else v.strip() for v in args.serve_buckets]
        if getattr(args, "serve_buckets", None)
        else [""]
    )
    space = SearchSpace(
        geometries=geometries,
        batches=batches,
        capacities=capacities,
        chunks=chunks,
        fused_ks=fused_ks,
        dps=dps,
        backup_updates=kernel_backends,
        per_samples=kernel_backends,
        precisions=precisions,
        serve_bucket_ladders=serve_ladders,
        tree_reuses=tree_reuses,
    )

    calibration = calibration_from_targets(
        args.calibrate or [], root_dir=args.root_dir
    )
    def say(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    say(
        f"tune: backend={backend} scale={plan.scale} mode={mode} "
        f"space={space.size()} candidates limit={fmt_bytes(limit)} "
        f"[{limit_source}] peak={peak or 'unknown'} TFLOP/s "
        f"[{peak_source}] calibration={','.join(calibration.sources)}"
    )

    result = run_search(
        space,
        plan.env,
        plan.model,
        plan.mcts,
        plan.train,
        limit,
        calibration=calibration,
        peak_tflops=peak,
        mode=mode,
        device_replay=plan.device_replay or mode == "megastep",
        progress=say,
    )

    run_name = args.run_name or f"tune_{plan.scale}"
    payload = None
    out_path = None
    if result.best is not None:
        from .autotune.search import candidate_mcts, materialize_candidate

        env_cfg, model_cfg, train_cfg = materialize_candidate(
            result.best, plan.env, plan.model, plan.train, mode
        )
        train_cfg = train_cfg.model_copy(update={"RUN_NAME": run_name})
        payload = build_tuned_preset(
            result,
            env_cfg,
            model_cfg,
            candidate_mcts(plan.mcts, result.best),
            train_cfg,
            scale=plan.scale,
            mode=mode,
            backend=backend,
            device_kind=device_kind,
            limit_bytes=limit,
            limit_source=limit_source,
            calibration=calibration,
            run_name=run_name,
        )
        out_path = Path(
            args.out
            or default_artifact_path(run_name, root_dir=args.root_dir)
        )
        write_tuned_preset(payload, out_path)

    if args.json:
        print(
            _json.dumps(
                {
                    "schema": "alphatriangle.tune_report.v1",
                    "scale": plan.scale,
                    "backend": backend,
                    "mode": mode,
                    "bytes_limit": limit,
                    "limit_source": limit_source,
                    "rows": result.rows,
                    "oracle_calls": result.oracle_calls,
                    "best": payload,
                    "artifact": str(out_path) if out_path else None,
                    "exit": 0 if result.best is not None else FIT_OVER,
                },
                default=str,
            )
        )
    else:
        hdr = (
            f"{'geometry':<9} {'B':>6} {'cap':>8} {'T':>4} {'K':>4} "
            f"{'dp':>3} {'pred games/h':>13} {'budget':>10}  status"
        )
        print(f"tune {plan.scale} on {backend} (mode {mode})")
        print(hdr)
        for row in result.rows:
            pred = row["predicted"] or {}
            gph = pred.get("games_per_hour")
            gph_s = (
                f"{gph:.1f}" if isinstance(gph, (int, float)) else "n/a"
            )
            budget = row["budget_total_bytes"]
            budget_s = fmt_bytes(budget) if budget else "n/a"
            detail = f" ({row['detail']})" if row["detail"] else ""
            print(
                f"{row['geometry']:<9} {row['sp_batch']:>6} "
                f"{row['capacity']:>8} {row['chunk']:>4} "
                f"{row['fused_k']:>4} {row['dp']:>3} {gph_s:>13} "
                f"{budget_s:>10}  {row['status']}{detail}"
            )
        if result.best is not None:
            pred = result.best_prediction or {}
            print(
                f"tune: best {result.best.label()} — predicted "
                f"{pred.get('games_per_hour', 0.0):.1f} games/h, "
                f"budget {fmt_bytes(result.best_budget['total_bytes'])} "
                f"of {fmt_bytes(limit)} "
                f"({result.oracle_calls} oracle call(s))"
            )
            print(f"tune: wrote {out_path}")
            print(
                f"tune: consume with `cli train --preset {out_path}`, "
                f"`cli warm {out_path}`, or BENCH_TUNED_PRESET={out_path}"
            )
        else:
            print(
                f"tune: no feasible candidate under {fmt_bytes(limit)} "
                f"({result.oracle_calls} oracle call(s), "
                f"{len(result.rows)} candidates examined)"
            )
    return 0 if result.best is not None else FIT_OVER


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="alphatriangle-tpu",
        description="TPU-native AlphaZero training for the triangle puzzle.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    _add_train_parser(sub)

    tb = sub.add_parser("tb", help="Launch TensorBoard over the runs root.")
    tb.add_argument("--root-dir", default=None)
    tb.add_argument("--port", type=int, default=6006)

    ml = sub.add_parser("ml", help="Launch MLflow UI (when installed).")
    ml.add_argument("--root-dir", default=None)
    ml.add_argument("--port", type=int, default=5000)

    sub.add_parser("devices", help="Show the JAX backend and devices.")

    watch = sub.add_parser(
        "watch",
        help="Live console for a training run (tails live_metrics.jsonl).",
    )
    watch.add_argument(
        "--run-name", default=None, help="Default: most recent run."
    )
    watch.add_argument("--root-dir", default=None)
    watch.add_argument("--interval", type=float, default=2.0)
    watch.add_argument(
        "--once", action="store_true", help="Render one frame and exit."
    )

    doctor = sub.add_parser(
        "doctor",
        help="Postmortem window forensics from the flight ring + "
        "health.json + wedge report — names the program a dead run "
        "hung inside; exit code is the verdict. No JAX import.",
    )
    doctor.add_argument(
        "run",
        nargs="?",
        default=None,
        help="Run name, run dir, or flight.jsonl path "
        "(default: latest run).",
    )
    doctor.add_argument("--root-dir", default=None)
    doctor.add_argument(
        "--json",
        action="store_true",
        help="Emit the verdict as one JSON line (tpu_watch.sh appends "
        "it to windows.jsonl).",
    )

    slo = sub.add_parser(
        "slo",
        help="Fleet SLO report: error budgets + multi-window burn-rate "
        "alerts from the fleet's ledgers. Exit 0 within budget, "
        "1 burning, 2 no data. No JAX import.",
    )
    slo.add_argument(
        "run",
        nargs="?",
        default=None,
        help="Run name or fleet-parent run dir (default: latest run).",
    )
    slo.add_argument("--root-dir", default=None)
    slo.add_argument(
        "--json",
        action="store_true",
        help="Emit the full alphatriangle.slo.v1 report as one JSON line.",
    )
    slo.add_argument(
        "--latency-threshold",
        type=float,
        default=500.0,
        help="p95 move-latency SLO threshold in ms (default 500).",
    )
    slo.add_argument(
        "--window",
        action="append",
        default=None,
        metavar="SECONDS:BURN",
        help="Override burn-rate windows (repeatable), e.g. 300:14.4 "
        "3600:6. Default: the SRE fast-page/slow-ticket pair.",
    )
    slo.add_argument(
        "--now",
        type=float,
        default=None,
        help="Evaluate at this epoch time instead of the newest record "
        "(replay the alert state mid-brownout).",
    )
    slo.add_argument(
        "--prom",
        action="store_true",
        help="Also (re)write the aggregated fleet.prom textfile.",
    )

    supervise = sub.add_parser(
        "supervise",
        help="Self-healing parent for train/league: restart a dead "
        "child from its latest committed checkpoint per the doctor "
        "verdict (backoff, OOM degrade, family quarantine, circuit "
        "breaker). JAX-free; events -> runs/<run>/supervisor.jsonl.",
    )
    supervise.add_argument(
        "--run-name",
        required=True,
        help="Run directory to supervise (injected into the child's "
        "argv when absent there).",
    )
    supervise.add_argument("--root-dir", default=None)
    supervise.add_argument(
        "--max-restarts",
        type=int,
        default=8,
        metavar="N",
        help="Total restart budget before giving up (exit 115).",
    )
    supervise.add_argument(
        "--circuit-breaker",
        type=int,
        default=3,
        metavar="N",
        help="Consecutive deaths without a new committed checkpoint "
        "that trip the breaker (exit 115).",
    )
    supervise.add_argument(
        "--backoff-base", type=float, default=5.0, metavar="SECONDS"
    )
    supervise.add_argument(
        "--backoff-max", type=float, default=300.0, metavar="SECONDS"
    )
    supervise.add_argument(
        "--quarantine-after",
        type=int,
        default=2,
        metavar="N",
        help="Wedges on one program family before its riskiest knob is "
        "quarantined (megastep -> sync, learner -> K=1, rollout -> "
        "sync rollouts).",
    )
    supervise.add_argument(
        "child",
        nargs=argparse.REMAINDER,
        help="Child subcommand + flags after '--' "
        "(default: train --run-name <run>).",
    )

    health = sub.add_parser(
        "health",
        help="Heartbeat check: pretty-print a run's health.json with a "
        "staleness verdict (exit 0 live / 1 stalled / 2 missing).",
    )
    health.add_argument(
        "run", nargs="?", default=None, help="Run name (default: latest)."
    )
    health.add_argument("--root-dir", default=None)
    health.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="Staleness deadline override (default: the run's "
        "watchdog deadline).",
    )
    health.add_argument(
        "--probe",
        action="store_true",
        help="Machine mode: one JSON line + exit-code contract "
        "(0 live / 1 stalled / 2 missing / 3 dispatch-overdue) — the "
        "probe the fleet router and external orchestrators share "
        "(docs/OBSERVABILITY.md).",
    )

    perf = sub.add_parser(
        "perf",
        help="Performance summary of a run's metrics ledger "
        "(p50/p95 step time, MFU, throughput trend).",
    )
    perf.add_argument(
        "run",
        nargs="?",
        default=None,
        help="Run name, run dir, or metrics.jsonl path "
        "(default: latest run).",
    )
    perf.add_argument("--root-dir", default=None)
    perf.add_argument(
        "--window",
        type=int,
        default=None,
        metavar="N",
        help="Summarize only the newest N utilization records "
        "(default: the whole run).",
    )
    perf.add_argument(
        "--json",
        action="store_true",
        help="Emit the summary as one JSON line (comparable input for "
        "`compare`).",
    )

    comp = sub.add_parser(
        "compare",
        help="Aligned-metric regression report between two runs (or a "
        "run and a BENCH_*.json / perf-summary snapshot); exit 0 "
        "parity, 1 regression, 2 unreadable.",
    )
    comp.add_argument(
        "run_a", help="Candidate: run name/dir, metrics.jsonl, or JSON."
    )
    comp.add_argument(
        "run_b", help="Baseline: run name/dir, metrics.jsonl, or JSON "
        "(e.g. BENCH_r05.json).",
    )
    comp.add_argument("--root-dir", default=None)
    comp.add_argument(
        "--threshold",
        type=float,
        default=0.1,
        metavar="FRAC",
        help="Regression tolerance: fail when a metric drops more than "
        "this fraction below the baseline (default 0.1).",
    )
    comp.add_argument(
        "--json", action="store_true", help="Emit the report as JSON."
    )
    comp.add_argument(
        "--metrics",
        default=None,
        metavar="M1[,M2...]",
        help="Compare only these metrics (default: the full aligned "
        "set, telemetry/perf.py COMPARE_METRICS). serve-smoke gates "
        "the serving SLO rows alone with this.",
    )

    trace = sub.add_parser(
        "trace",
        help="Summarize a run's host span trace (trace.json; "
        "Perfetto/chrome-loadable).",
    )
    trace.add_argument(
        "run", nargs="?", default=None, help="Run name (default: latest)."
    )
    trace.add_argument("--root-dir", default=None)
    trace.add_argument("--top", type=int, default=20)
    trace.add_argument(
        "--fleet",
        action="store_true",
        help="Fuse a fleet-parent run dir (parent route brackets + "
        "fleet.jsonl + every replica's flight ring and trace.json, "
        "clock-calibrated) into one Perfetto timeline with flow "
        "arrows per trace_id (trace_fleet.json).",
    )

    an = sub.add_parser(
        "analyze", help="Summarize per-phase timer dumps from a profile run."
    )
    an.add_argument("profile_dir", help="runs/<run>/profile_data directory.")
    an.add_argument("--top", type=int, default=20)

    ev = sub.add_parser(
        "eval", help="Arena evaluation of a checkpoint (greedy MCTS play)."
    )
    ev.add_argument("--checkpoint", default=None, metavar="PATH")
    ev.add_argument("--run-name", default=None)
    ev.add_argument(
        "--vs-checkpoint",
        default=None,
        metavar="PATH",
        help="Head-to-head opponent checkpoint (plays the same paired "
        "hands).",
    )
    ev.add_argument(
        "--vs-run",
        default=None,
        help="Head-to-head opponent: latest checkpoint of this run.",
    )
    ev.add_argument("--root-dir", default=None)
    ev.add_argument("--games", type=int, default=64)
    ev.add_argument("--sims", type=int, default=64)
    ev.add_argument("--max-moves", type=int, default=200)
    ev.add_argument("--seed", type=int, default=0)
    ev.add_argument(
        "--gumbel",
        action="store_true",
        help="Evaluate with exploit-mode Gumbel search (deterministic "
        "logits + sigma(q) argmax) instead of greedy PUCT.",
    )
    ev.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )

    warm = sub.add_parser(
        "warm",
        help="AOT-precompile the hot bench/training programs (rollout "
        "chunk, learner step, fused groups) into the executable cache "
        "so the next bench/run skips first-dispatch compiles.",
    )
    warm.add_argument(
        "target",
        nargs="?",
        default="auto",
        help="What to warm: 'auto' = the bench scale for this backend "
        "(honors ambient BENCH_* knobs), 'smoke'/'cpu' = the reduced "
        "scales, 1..5 = a BASELINE preset (config/presets.py), or a "
        "tuned_preset.json path from `cli tune`.",
    )
    warm.add_argument(
        "--jobs",
        type=int,
        default=4,
        metavar="N",
        help="Programs compiled in parallel threads (XLA releases the "
        "GIL during compilation).",
    )
    warm.add_argument(
        "--programs",
        default=None,
        metavar="SUBSTR[,SUBSTR...]",
        help="Only warm programs whose name contains one of these "
        "substrings (e.g. 'self_play,learner_step').",
    )
    warm.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )

    fit = sub.add_parser(
        "fit",
        help="OOM pre-flight: compose the static per-device memory "
        "budget (params + opt state + replay ring + AOT-analyzed "
        "program memory) against the device byte limit; exit 0 fits / "
        "1 over budget / 2 unknown device limit.",
    )
    fit.add_argument(
        "target",
        nargs="?",
        default="auto",
        help="Scale to check: 'auto' = the bench scale for this "
        "backend (honors ambient BENCH_* knobs), 'smoke'/'cpu' = the "
        "reduced scales, 1..5 = a BASELINE preset, or a "
        "tuned_preset.json path from `cli tune`.",
    )
    fit.add_argument(
        "--limit-gb",
        type=float,
        default=None,
        metavar="GIB",
        help="Assert a per-device byte limit (GiB) instead of asking "
        "the backend (also: ALPHATRIANGLE_DEVICE_BYTES_LIMIT, bytes).",
    )
    fit.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )
    fit.add_argument(
        "--json", action="store_true", help="Emit the report as JSON."
    )
    fit.add_argument(
        "--serve",
        action="store_true",
        help="Additionally AOT-analyze the policy service's "
        "serve/b<B> search program and persist its .mem.json sidecar "
        "(the `cli serve` pre-flight reads it; docs/SERVING.md).",
    )

    serve = sub.add_parser(
        "serve",
        help="Policy-serving front end: continuous-batching inference "
        "service over the batched wave search, with AOT-warmed "
        "startup, OOM pre-flight, heartbeat, and per-request latency "
        "SLOs in the metrics ledger (docs/SERVING.md).",
    )
    serve.add_argument(
        "--run-name",
        default=None,
        help="Serve this run's latest checkpoint (and its board/net "
        "configs); with --reload-every, newer checkpoints hot-swap in.",
    )
    serve.add_argument("--checkpoint", default=None, metavar="PATH")
    serve.add_argument("--root-dir", default=None)
    serve.add_argument(
        "--serve-run-name",
        default=None,
        help="Run dir for the service's own telemetry "
        "(default: serve_<run-name> or 'serve').",
    )
    serve.add_argument(
        "--slots",
        type=int,
        default=64,
        metavar="B",
        help="Concurrent session slots = the compiled serve/b<B> "
        "search batch shape (default 64).",
    )
    serve.add_argument(
        "--buckets",
        default=None,
        metavar="RUNGS",
        help="Serve-shape ladder as a CSV rung list (e.g. 16,64,256 — "
        "serving/buckets.py). The micro-batcher walks between rungs "
        "with sustained load; every rung is AOT-warmed up front so a "
        "switch never recompiles. Default: a single fixed rung at "
        "--slots.",
    )
    serve.add_argument("--sims", type=int, default=64)
    serve.add_argument(
        "--sessions",
        type=int,
        default=96,
        metavar="N",
        help="Simulated sessions per traffic wave (the smoke serves "
        "exactly one wave).",
    )
    serve.add_argument("--max-moves", type=int, default=200)
    serve.add_argument("--seed", type=int, default=0)
    serve.add_argument(
        "--gumbel",
        action="store_true",
        help="Serve exploit-mode Gumbel search instead of greedy PUCT.",
    )
    serve.add_argument(
        "--smoke",
        action="store_true",
        help="Bounded CI mode: serve one wave of --sessions simulated "
        "sessions with churn, assert the latency ledger landed, exit "
        "0/1 (make serve-smoke drives this on CPU).",
    )
    serve.add_argument(
        "--duration",
        type=float,
        default=None,
        metavar="SECONDS",
        help="Serve traffic waves until this wall budget elapses "
        "(default: one wave, or Ctrl-C).",
    )
    serve.add_argument(
        "--tick-every",
        type=int,
        default=8,
        metavar="DISPATCHES",
        help="Ledger/heartbeat tick cadence in dispatches (default 8).",
    )
    serve.add_argument(
        "--reload-every",
        type=int,
        default=32,
        metavar="DISPATCHES",
        help="Poll the run's checkpoints for hot weight reload every "
        "N dispatches (0 disables; needs --run-name).",
    )
    serve.add_argument(
        "--limit-gb",
        type=float,
        default=None,
        metavar="GIB",
        help="Pre-flight device byte limit override "
        "(also: ALPHATRIANGLE_DEVICE_BYTES_LIMIT).",
    )
    serve.add_argument(
        "--no-warm",
        action="store_true",
        help="Skip the AOT warm-start step.",
    )
    serve.add_argument(
        "--no-preflight",
        action="store_true",
        help="Skip the OOM pre-flight gate.",
    )
    serve.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )

    fleet = sub.add_parser(
        "fleet",
        help="Fault-tolerant serve fleet: N PolicyService replica "
        "subprocesses behind a health-gated least-queue-depth router "
        "with retry/hedge/shed, verdict-driven replica restarts, and "
        "a crash-safe fleet.jsonl decision ledger (docs/SERVING.md "
        "'Fleet'). The parent never imports JAX.",
    )
    fleet.add_argument(
        "--run-name",
        default="fleet",
        help="Fleet run dir name (replica run dirs nest inside; a "
        "configs.json there supplies the board/net).",
    )
    fleet.add_argument("--root-dir", default=None)
    fleet.add_argument("--replicas", type=int, default=2, metavar="N")
    fleet.add_argument(
        "--slots",
        type=int,
        default=8,
        metavar="B",
        help="Session slots per replica = its compiled serve/b<B> "
        "bucket (a quarantined replica respawns onto the next ladder "
        "rung down).",
    )
    fleet.add_argument(
        "--buckets",
        default=None,
        metavar="RUNGS",
        help="Serve-shape ladder as a CSV rung list shared by every "
        "replica's micro-batcher AND the quarantine walk-down "
        "(serving/buckets.py). Default: the halving ladder under "
        "--slots (reproduces the legacy 0.5-multiplier buckets).",
    )
    fleet.add_argument("--sims", type=int, default=4)
    fleet.add_argument("--seed", type=int, default=0)
    fleet.add_argument(
        "--requests",
        type=int,
        default=32,
        metavar="N",
        help="Episode requests in the storm.",
    )
    fleet.add_argument("--concurrency", type=int, default=8)
    fleet.add_argument("--max-moves", type=int, default=12)
    fleet.add_argument(
        "--timeout",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="Per-attempt request timeout (a timed-out attempt "
        "retries on a different replica).",
    )
    fleet.add_argument(
        "--retries",
        type=int,
        default=2,
        help="Retry budget per request after the first attempt.",
    )
    fleet.add_argument(
        "--route-backoff-base", type=float, default=0.1, metavar="SECONDS"
    )
    fleet.add_argument(
        "--route-backoff-max", type=float, default=2.0, metavar="SECONDS"
    )
    fleet.add_argument(
        "--hedge-after",
        type=float,
        default=None,
        metavar="SECONDS",
        help="Hedge a straggling request onto a second replica after "
        "this long; first result wins (default: off).",
    )
    fleet.add_argument(
        "--max-queue",
        type=int,
        default=64,
        metavar="N",
        help="Bounded admission: in-flight requests past this are "
        "shed with rejection code 'queue-full'.",
    )
    fleet.add_argument(
        "--probe-deadline",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="Heartbeat staleness deadline for the routability probe.",
    )
    fleet.add_argument(
        "--poll",
        type=float,
        default=0.25,
        metavar="SECONDS",
        help="Fleet monitor poll cadence (deaths, probes, respawns).",
    )
    fleet.add_argument(
        "--spawn-timeout",
        type=float,
        default=300.0,
        metavar="SECONDS",
        help="Budget for a replica to warm + report ready.",
    )
    fleet.add_argument(
        "--settle",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="Post-storm wait for pending respawn/readmit chains to "
        "land on fleet.jsonl.",
    )
    fleet.add_argument("--max-restarts", type=int, default=8)
    fleet.add_argument("--circuit-breaker", type=int, default=3)
    fleet.add_argument(
        "--backoff-base",
        type=float,
        default=5.0,
        metavar="SECONDS",
        help="Replica restart backoff base (RecoveryPolicy).",
    )
    fleet.add_argument(
        "--backoff-max", type=float, default=300.0, metavar="SECONDS"
    )
    fleet.add_argument(
        "--quarantine-after",
        type=int,
        default=2,
        metavar="N",
        help="Wedges on the serve family before the replica respawns "
        "onto a halved bucket (SERVE_SLOTS__scale).",
    )
    fleet.add_argument("--tick-every", type=int, default=8)
    fleet.add_argument(
        "--replica-health-interval", type=float, default=1.0
    )
    fleet.add_argument(
        "--replica-dispatch-min-deadline", type=float, default=60.0
    )
    fleet.add_argument(
        "--replica-dispatch-first-deadline", type=float, default=900.0
    )
    fleet.add_argument(
        "--replica-watchdog-poll", type=float, default=5.0
    )
    fleet.add_argument(
        "--chaos-kill-after",
        type=int,
        default=0,
        metavar="N",
        help="SIGKILL one replica after N completed requests "
        "(the fleet smoke's deterministic chaos trigger; 0 = off).",
    )
    fleet.add_argument(
        "--reload-after",
        type=int,
        default=0,
        metavar="N",
        help="Start a rolling weight swap after N completed requests "
        "(0 = off).",
    )
    fleet.add_argument(
        "--smoke",
        action="store_true",
        help="Gate on the zero-lost-requests invariant "
        "(make fleet-smoke drives this on CPU).",
    )

    league = sub.add_parser(
        "league",
        help="Experience-flywheel mode: learner + matchmade league "
        "games through a PolicyService in one process, served "
        "trajectories flowing into the replay ring alongside "
        "self-play (docs/LEAGUE.md).",
    )
    league.add_argument(
        "--pool-from",
        required=True,
        metavar="RUN",
        help="Seed the opponent pool from this run's checkpoints (its "
        "configs.json also supplies the board/net geometry).",
    )
    league.add_argument("--run-name", default=None)
    league.add_argument("--root-dir", default=None)
    league.add_argument("--steps", type=int, default=None, metavar="N",
                        help="MAX_TRAINING_STEPS for the learner.")
    league.add_argument(
        "--mix",
        type=float,
        default=None,
        metavar="RATIO",
        help="Fraction of iterations that play a league round instead "
        "of a self-play chunk (default 0.25).",
    )
    league.add_argument(
        "--slots",
        type=int,
        default=None,
        metavar="B",
        help="League service session slots (= serve/b<B> shape).",
    )
    league.add_argument(
        "--games",
        type=int,
        default=None,
        metavar="G",
        help="Games per side per matchmade pairing.",
    )
    league.add_argument("--sims", type=int, default=None)
    league.add_argument("--max-moves", type=int, default=None)
    league.add_argument(
        "--reload-every",
        type=int,
        default=None,
        metavar="STEPS",
        help="Broadcast fresh learner params to the league service "
        "every N learner steps (default 8).",
    )
    league.add_argument(
        "--staleness-window",
        type=int,
        default=None,
        metavar="RELOADS",
        help="Drop harvested rows more than this many reloads behind "
        "the learner (default 4; negative disables).",
    )
    league.add_argument("--promotion-games", type=int, default=None)
    league.add_argument("--promotion-win-rate", type=float, default=None)
    league.add_argument("--exploration-floor", type=float, default=None)
    league.add_argument("--seed", type=int, default=None)
    league.add_argument("--self-play-batch", type=int, default=None)
    league.add_argument("--batch-size", type=int, default=None)
    league.add_argument("--buffer-capacity", type=int, default=None)
    league.add_argument("--min-buffer", type=int, default=None)
    league.add_argument("--rollout-chunk", type=int, default=None)
    league.add_argument("--checkpoint-freq", type=int, default=None)
    league.add_argument(
        "--device-replay", default=None, choices=["auto", "on", "off"]
    )
    league.add_argument("--no-telemetry", action="store_true")
    league.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )

    lint = sub.add_parser(
        "lint",
        help="graftlint: AST-based JAX-hazard analyzer (donation, host "
        "syncs, placement, flight coverage, debug artifacts, RNG) — "
        "no JAX import; exit 0 clean / 1 findings / 2 parse error "
        "(docs/ANALYSIS.md).",
    )
    lint.add_argument(
        "path",
        nargs="?",
        default=None,
        help="Tree to lint (default: the installed alphatriangle_tpu "
        "package).",
    )
    lint.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="NAME",
        help="Run only this rule (repeatable).",
    )
    lint.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help="Baseline file of grandfathered finding keys (default: "
        "lint_baseline.json beside the linted tree). Stale entries "
        "fail the lint.",
    )
    lint.add_argument(
        "--write-baseline",
        action="store_true",
        help="Grandfather every current finding into the baseline file "
        "and exit 0.",
    )
    lint.add_argument(
        "--json",
        action="store_true",
        help='One-line JSON verdict (leads with "schema": '
        f'"alphatriangle.lint.v1") — what tpu_watch.sh folds into '
        "windows.jsonl.",
    )

    mem = sub.add_parser(
        "mem",
        help="Memory-attribution table for a run (programs, train "
        "state, replay ring, observed in-use/peak) from its "
        "metrics.jsonl alone — no JAX import.",
    )
    mem.add_argument(
        "run",
        nargs="?",
        default=None,
        help="Run name, run dir, or metrics.jsonl path "
        "(default: latest run).",
    )
    mem.add_argument("--root-dir", default=None)
    mem.add_argument(
        "--json", action="store_true", help="Emit records + budget as JSON."
    )

    roofline = sub.add_parser(
        "roofline",
        help="Roofline attribution for a run: per-program intensity "
        "vs machine balance + chip-idle gap forensics, from its "
        "artifacts alone — no JAX import.",
    )
    roofline.add_argument(
        "run",
        nargs="?",
        default=None,
        help="Run name, run dir, or metrics.jsonl path "
        "(default: latest run).",
    )
    roofline.add_argument("--root-dir", default=None)
    roofline.add_argument(
        "--json",
        action="store_true",
        help="Emit the roofline summary as one JSON line.",
    )

    tune = sub.add_parser(
        "tune",
        help="Fit-driven offline autotuner: search batch/capacity/"
        "chunk/K/dp/geometry for the feasible config maximizing "
        "predicted games/h — AOT memory analysis as the oracle, no "
        "chip execution — and emit a tuned_preset.json "
        "(docs/AUTOTUNE.md).",
    )
    tune.add_argument(
        "target",
        nargs="?",
        default="auto",
        help="Base scale to search around: 'auto' = the bench scale "
        "for this backend, 'smoke'/'cpu' = the reduced scales, "
        "1..5 = a BASELINE preset.",
    )
    tune.add_argument(
        "--limit-gb",
        type=float,
        default=None,
        metavar="GIB",
        help="Per-device byte limit (GiB) the search must fit under "
        "(default: backend-reported; also "
        "ALPHATRIANGLE_DEVICE_BYTES_LIMIT, bytes).",
    )
    tune.add_argument(
        "--smoke",
        action="store_true",
        help="Tiny lattice for CI: a couple of oracle compiles, not a "
        "sweep (make tune-smoke).",
    )
    tune.add_argument(
        "--json",
        action="store_true",
        help="Emit the full search report (rows + winner) as JSON.",
    )
    tune.add_argument(
        "--out",
        default=None,
        metavar="PATH",
        help="Write tuned_preset.json here "
        "(default: runs/<run-name>/tuned_preset.json).",
    )
    tune.add_argument("--run-name", default=None)
    tune.add_argument("--root-dir", default=None)
    tune.add_argument(
        "--batches",
        default=None,
        help="Override the SELF_PLAY_BATCH_SIZE axis (comma-separated).",
    )
    tune.add_argument(
        "--capacities",
        default=None,
        help="Override the BUFFER_CAPACITY axis (comma-separated).",
    )
    tune.add_argument(
        "--chunks",
        default=None,
        help="Override the rollout chunk T axis (comma-separated).",
    )
    tune.add_argument(
        "--fused-k",
        default=None,
        help="Override the fused learner K axis (comma-separated).",
    )
    tune.add_argument(
        "--dp",
        default=None,
        help="Override the data-parallel shard axis (comma-separated).",
    )
    tune.add_argument(
        "--geometries",
        default=None,
        help="Board geometry presets to search (comma-separated names "
        "from config.GEOMETRY_PRESETS, or 'plan' = the scale's board).",
    )
    tune.add_argument(
        "--kernel-backends",
        default=None,
        metavar="BACKENDS",
        help="Kernel lowerings to search for backup_update and "
        "PER_SAMPLE_BACKEND (comma-separated from xla,pallas — "
        "docs/KERNELS.md). Free axes: memory-neutral variants share "
        "oracle results. Default: xla only.",
    )
    tune.add_argument(
        "--precisions",
        default=None,
        metavar="DTYPES",
        help="INFERENCE_PRECISION values to search (comma-separated "
        "from float32,bfloat16,int8 — int8 is weight-only per-channel "
        "quantization, docs/KERNELS.md). Default: float32 only.",
    )
    tune.add_argument(
        "--serve-buckets",
        action="append",
        default=None,
        metavar="RUNGS",
        help="Serve-shape ladders to search (repeatable; each a CSV "
        "rung list like 64,256,1024 — serving/buckets.py, or 'off' for "
        "the fixed single-rung shape). Serve-side free axis: ladders "
        "share training feasibility answers. Default: off only.",
    )
    tune.add_argument(
        "--tree-reuse",
        default=None,
        metavar="VALUES",
        help="MCTS subtree-reuse settings to search (comma-separated "
        "from off,on — docs/KERNELS.md). Reuse widens the tree planes, "
        "so 'on' candidates get their own feasibility-oracle answers. "
        "Default: off only.",
    )
    tune.add_argument(
        "--calibrate",
        action="append",
        default=None,
        metavar="RUN_OR_JSON",
        help="Calibrate the throughput model against these runs / perf "
        "summaries (repeatable; accepts anything `cli perf compare` "
        "does). Default: the model's conservative built-ins.",
    )
    tune.add_argument(
        "--mode",
        default="auto",
        choices=["auto", "sync", "megastep"],
        help="Loop shape being tuned (auto = megastep when the bench "
        "plan would run device replay).",
    )
    tune.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )

    play = sub.add_parser(
        "play", help="Interactive text play on the default board."
    )
    play.add_argument("--seed", type=int, default=0)
    play.add_argument(
        "--engine", choices=["auto", "native", "jax"], default="auto"
    )
    play.add_argument(
        "--script",
        default=None,
        help="Semicolon-separated scripted moves ('0 0 0;1 2 3'); "
        "plays them then exits (demo/testing).",
    )

    args = parser.parse_args(argv)
    handlers = {
        "train": cmd_train,
        "tb": cmd_tb,
        "ml": cmd_ml,
        "devices": cmd_devices,
        "watch": cmd_watch,
        "health": cmd_health,
        "doctor": cmd_doctor,
        "slo": cmd_slo,
        "supervise": cmd_supervise,
        "perf": cmd_perf,
        "compare": cmd_compare,
        "trace": cmd_trace,
        "analyze": cmd_analyze,
        "eval": cmd_eval,
        "play": cmd_play,
        "tune": cmd_tune,
        "warm": cmd_warm,
        "fit": cmd_fit,
        "serve": cmd_serve,
        "fleet": cmd_fleet,
        "league": cmd_league,
        "mem": cmd_mem,
        "roofline": cmd_roofline,
        "lint": cmd_lint,
    }
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
