"""Command-line interface (reference `alphatriangle/cli.py:31-326`).

Subcommands mirror the reference's Typer app: `train` (config
overrides -> `run_training`), `tb` (launch TensorBoard on the runs
root), `ml` (MLflow launcher — degrades with a clear message when
MLflow isn't installed, as in this TPU image). The reference's `ray`
command has no equivalent: there is no actor runtime to inspect; the
device story lives in `jax.devices()` (printed by `devices`).

Console script: `alphatriangle-tpu` (pyproject `[project.scripts]`,
reference `pyproject.toml:53-54`).
"""

import argparse
import logging
import subprocess
import sys

logger = logging.getLogger(__name__)


def _add_train_parser(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser("train", help="Run a training session.")
    # Reference override surface (`cli.py:40-74`).
    p.add_argument("--run-name", default=None, help="Run directory name.")
    p.add_argument("--seed", type=int, default=None, help="Random seed.")
    p.add_argument(
        "--log-level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR"],
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="Capture a jax.profiler trace + per-phase timers into "
        "runs/<run>/profile_data/.",
    )
    # TPU-native sizing knobs.
    p.add_argument("--max-steps", type=int, default=None)
    p.add_argument("--self-play-batch", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--buffer-capacity", type=int, default=None)
    p.add_argument("--min-buffer", type=int, default=None)
    p.add_argument("--rollout-chunk", type=int, default=None)
    p.add_argument("--no-per", action="store_true")
    p.add_argument(
        "--no-auto-resume",
        action="store_true",
        help="Start fresh instead of resuming the latest run.",
    )
    p.add_argument("--load-checkpoint", default=None, metavar="PATH")
    p.add_argument("--load-buffer", default=None, metavar="PATH")
    p.add_argument("--root-dir", default=None, help="Runs root directory.")
    p.add_argument("--no-tensorboard", action="store_true")
    p.add_argument(
        "--device",
        default=None,
        choices=["auto", "tpu", "cpu"],
        help="Compute platform; cpu forces the CPU backend even when an "
        "accelerator plugin is present.",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="Join a jax.distributed cluster (auto-discovery on TPU "
        "pods; use --coordinator/--num-processes/--process-id for "
        "explicit clusters).",
    )
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)


def cmd_train(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig, TrainConfig
    from .parallel.distributed import DistributedConfig
    from .training.runner import run_training

    overrides: dict = {}
    if args.run_name is not None:
        overrides["RUN_NAME"] = args.run_name
    if args.seed is not None:
        overrides["RANDOM_SEED"] = args.seed
    if args.max_steps is not None:
        overrides["MAX_TRAINING_STEPS"] = args.max_steps
    if args.self_play_batch is not None:
        overrides["SELF_PLAY_BATCH_SIZE"] = args.self_play_batch
    if args.batch_size is not None:
        overrides["BATCH_SIZE"] = args.batch_size
    if args.buffer_capacity is not None:
        overrides["BUFFER_CAPACITY"] = args.buffer_capacity
    if args.min_buffer is not None:
        overrides["MIN_BUFFER_SIZE_TO_TRAIN"] = args.min_buffer
    if args.rollout_chunk is not None:
        overrides["ROLLOUT_CHUNK_MOVES"] = args.rollout_chunk
    if args.no_per:
        overrides["USE_PER"] = False
    if args.no_auto_resume:
        overrides["AUTO_RESUME_LATEST"] = False
    if args.load_checkpoint is not None:
        overrides["LOAD_CHECKPOINT_PATH"] = args.load_checkpoint
    if args.load_buffer is not None:
        overrides["LOAD_BUFFER_PATH"] = args.load_buffer
    if args.profile:
        overrides["PROFILE_WORKERS"] = True
    if args.device is not None:
        overrides["DEVICE"] = args.device
    train_config = TrainConfig(**overrides)

    persistence_config = None
    if args.root_dir is not None:
        persistence_config = PersistenceConfig(
            ROOT_DATA_DIR=args.root_dir, RUN_NAME=train_config.RUN_NAME
        )
    distributed_config = None
    if args.distributed or args.coordinator is not None:
        distributed_config = DistributedConfig(
            ENABLED=True,
            COORDINATOR_ADDRESS=args.coordinator,
            NUM_PROCESSES=args.num_processes,
            PROCESS_ID=args.process_id,
        )
    return run_training(
        train_config=train_config,
        persistence_config=persistence_config,
        distributed_config=distributed_config,
        log_level=args.log_level,
        use_tensorboard=not args.no_tensorboard,
    )


def _launch_ui(tool: str, argv: list[str]) -> int:
    """Run a dashboard tool in the foreground (reference `cli.py:85-137`)."""
    try:
        __import__(tool)
    except ImportError:
        print(
            f"{tool} is not installed in this environment. "
            f"Install it to use this command.",
            file=sys.stderr,
        )
        return 1
    cmd = [sys.executable, "-m", tool, *argv]
    print(f"Launching: {' '.join(cmd)} (Ctrl-C to stop)")
    try:
        return subprocess.call(cmd)
    except KeyboardInterrupt:
        return 0


def cmd_tb(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig

    root = args.root_dir or PersistenceConfig().ROOT_DATA_DIR
    return _launch_ui(
        "tensorboard", ["--logdir", root, "--port", str(args.port)]
    )


def cmd_ml(args: argparse.Namespace) -> int:
    from .config import PersistenceConfig

    root = args.root_dir or PersistenceConfig().ROOT_DATA_DIR
    return _launch_ui(
        "mlflow", ["ui", "--backend-store-uri", root, "--port", str(args.port)]
    )


def cmd_devices(_args: argparse.Namespace) -> int:
    import jax

    from .utils.helpers import enforce_platform

    # Honor JAX_PLATFORMS=cpu even when a site hook re-forces the
    # accelerator plugin (whose init can hang on a sick chip).
    enforce_platform("auto")
    print(f"backend: {jax.default_backend()}")
    for d in jax.devices():
        print(f"  {d.id}: {getattr(d, 'device_kind', d.platform)}")
    return 0


def cmd_analyze(args: argparse.Namespace) -> int:
    from .profiling import analyze_profile_dir

    return analyze_profile_dir(args.profile_dir, top=args.top)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="alphatriangle-tpu",
        description="TPU-native AlphaZero training for the triangle puzzle.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    _add_train_parser(sub)

    tb = sub.add_parser("tb", help="Launch TensorBoard over the runs root.")
    tb.add_argument("--root-dir", default=None)
    tb.add_argument("--port", type=int, default=6006)

    ml = sub.add_parser("ml", help="Launch MLflow UI (when installed).")
    ml.add_argument("--root-dir", default=None)
    ml.add_argument("--port", type=int, default=5000)

    sub.add_parser("devices", help="Show the JAX backend and devices.")

    an = sub.add_parser(
        "analyze", help="Summarize per-phase timer dumps from a profile run."
    )
    an.add_argument("profile_dir", help="runs/<run>/profile_data directory.")
    an.add_argument("--top", type=int, default=20)

    args = parser.parse_args(argv)
    handlers = {
        "train": cmd_train,
        "tb": cmd_tb,
        "ml": cmd_ml,
        "devices": cmd_devices,
        "analyze": cmd_analyze,
    }
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
