"""Host-side single-game wrapper with the reference's GameState API.

Parity surface for the C++ `trianglengin.GameState` as observed at its
call sites (`alphatriangle/rl/self_play/worker.py:190-377`,
`alphatriangle/features/extractor.py:25-118`,
`tests/nn/test_network.py:151`). The wrapper delegates every transition
to the jitted single-game `TriangleEnv` functions, so host play and
device self-play share one rules implementation by construction.

Not a hot path: on-device batched self-play never touches this class.
It exists for interactive play, debugging, tests, and API familiarity.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..config.env_config import EnvConfig
from .engine import EnvState, TriangleEnv

# One compiled engine per EnvConfig (jit caches live on the env instance).
_ENV_CACHE: dict[str, TriangleEnv] = {}


def get_env(cfg: EnvConfig) -> TriangleEnv:
    key = cfg.model_dump_json()
    env = _ENV_CACHE.get(key)
    if env is None:
        env = _ENV_CACHE[key] = TriangleEnv(cfg)
    return env


class Shape:
    """A placeable shape (reference `trianglengin.Shape` surface)."""

    def __init__(self, triangles: list[tuple[int, int, bool]], color_id: int = 0):
        self.triangles = triangles  # list of (r, c, is_up)
        self.color_id = color_id

    def bbox(self) -> tuple[int, int, int, int]:
        """(min_r, min_c, max_r, max_c) over the shape's triangles."""
        rs = [t[0] for t in self.triangles]
        cs = [t[1] for t in self.triangles]
        return min(rs), min(cs), max(rs), max(cs)

    def __len__(self) -> int:
        return len(self.triangles)

    def __repr__(self) -> str:
        return f"Shape({len(self.triangles)} tris, color={self.color_id})"


class GameState:
    """One interactive game over the jitted functional engine."""

    def __init__(
        self,
        env_config: EnvConfig | None = None,
        initial_seed: int = 0,
        _state: EnvState | None = None,
    ):
        self.env_config = env_config or EnvConfig()
        self._env = get_env(self.env_config)
        if _state is not None:
            self._state = _state
        else:
            self._state = self._env.reset_1(jax.random.PRNGKey(initial_seed))

    # --- queries ----------------------------------------------------------

    def is_over(self) -> bool:
        return bool(self._state.done)

    def get_game_over_reason(self) -> str | None:
        if not self.is_over():
            return None
        return "no valid placement for any remaining shape"

    def valid_actions(self) -> list[int]:
        mask = np.asarray(self._env.valid_mask_1(self._state))
        return [int(a) for a in np.flatnonzero(mask)]

    def valid_action_mask(self) -> np.ndarray:
        """(action_dim,) bool — dense form (TPU-native extension)."""
        return np.asarray(self._env.valid_mask_1(self._state))

    def game_score(self) -> float:
        return float(self._state.score)

    @property
    def current_step(self) -> int:
        return int(self._state.step_count)

    def get_last_cleared_triangles(self) -> int:
        return int(self._state.last_cleared)

    def get_grid_data_np(self) -> dict[str, np.ndarray]:
        """Dense grid views: occupied / death / color_id (copies)."""
        return {
            "occupied": self._env.unpack_grid_np(
                np.asarray(self._state.occupied)
            ),
            "death": self._env.geometry.death.copy(),
            "color_id": np.asarray(self._state.color),
        }

    def get_shapes(self) -> list[Shape | None]:
        """Current hand; None for consumed slots."""
        out: list[Shape | None] = []
        bank = self._env.bank
        for k in range(self.env_config.NUM_SHAPE_SLOTS):
            sidx = int(self._state.shape_idx[k])
            if sidx < 0:
                out.append(None)
                continue
            tris = [
                (int(r), int(c), (int(r) + int(c)) % 2 == 0)
                for r, c in bank.shapes[sidx]
            ]
            out.append(Shape(tris, color_id=int(self._state.shape_color[k])))
        return out

    # --- transitions ------------------------------------------------------

    def step(self, action: int) -> tuple[float, bool]:
        """Apply `action`; returns (reward, done)."""
        state, reward, done = self._env.step_1(self._state, jnp.int32(action))
        self._state = state
        return float(reward), bool(done)

    def copy(self) -> "GameState":
        return GameState(self.env_config, _state=self._state)

    def __repr__(self) -> str:
        return (
            f"GameState(step={self.current_step}, score={self.game_score():.1f}, "
            f"over={self.is_over()})"
        )
