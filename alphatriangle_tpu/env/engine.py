"""Batched pure-functional triangle puzzle engine.

TPU-native replacement for the reference's per-process C++
`trianglengin.GameState` (surface at
`alphatriangle/rl/self_play/worker.py:190-377`): game state is a
struct-of-arrays pytree, and `reset` / `step` / `valid_action_mask` are
pure jittable functions, vmappable across a whole batch of games so
self-play steps thousands of boards per device dispatch.

Semantics (behavior contract, pinned by tests/test_env.py):
- Action encoding: `slot * ROWS * COLS + r * COLS + c`
  (reference: `alphatriangle/nn/model.py:122-125`).
- A placement is valid iff the slot holds a shape and every triangle of
  the shape lands in-bounds on a playable, unoccupied cell of matching
  orientation (up/down parity).
- After placement every full line (geometry.build_line_masks) clears
  simultaneously; reward = placed * REWARD_PER_PLACED_TRIANGLE +
  cleared * REWARD_PER_CLEARED_TRIANGLE, both also added to the score.
- The consumed slot empties; when all slots are empty the hand refills
  with NUM_SHAPE_SLOTS uniform draws from the shape bank.
- The game ends (PENALTY_GAME_OVER added to reward, not score) when no
  remaining shape has a valid placement. Stepping an invalid action
  ends the game the same way. Stepping a finished game is a no-op.
"""

import jax
import jax.numpy as jnp
from flax import struct

from ..config.env_config import EnvConfig
from .geometry import EnvGeometry, build_geometry
from .shapes import ShapeBank, build_shape_bank


@struct.dataclass
class EnvState:
    """One game's state (add a leading batch dim via vmap)."""

    occupied: jax.Array  # (R, C) bool
    color: jax.Array  # (R, C) int8; -1 where empty
    shape_idx: jax.Array  # (SLOTS,) int32 into the bank; -1 = consumed
    shape_color: jax.Array  # (SLOTS,) int8
    score: jax.Array  # () float32
    step_count: jax.Array  # () int32
    done: jax.Array  # () bool
    last_cleared: jax.Array  # () int32 triangles cleared by the last step
    key: jax.Array  # PRNG key driving shape refills


class TriangleEnv:
    """Static env: config + precomputed geometry + jitted transition fns.

    Instances are cheap, immutable, and safe to share across threads;
    all mutable state lives in `EnvState` pytrees owned by the caller.
    """

    def __init__(self, cfg: EnvConfig):
        self.cfg = cfg
        self.bank: ShapeBank = build_shape_bank(cfg)
        self.geometry: EnvGeometry = build_geometry(cfg)
        self.rows, self.cols = cfg.ROWS, cfg.COLS
        self.num_slots = cfg.NUM_SHAPE_SLOTS
        self.action_dim = cfg.action_dim

        # Device-side static geometry (XLA embeds these as constants).
        self._tri_r = jnp.asarray(self.bank.tri_r)
        self._tri_c = jnp.asarray(self.bank.tri_c)
        self._tri_up = jnp.asarray(self.bank.tri_up)
        self._tri_valid = jnp.asarray(self.bank.tri_valid)
        self._n_tris = jnp.asarray(self.bank.n_tris)
        self._death = jnp.asarray(self.geometry.death)
        self._line_masks = jnp.asarray(self.geometry.line_masks)
        rr, cc = jnp.meshgrid(
            jnp.arange(self.rows), jnp.arange(self.cols), indexing="ij"
        )
        self._rr, self._cc = rr, cc

        # Jitted batched entry points (leading batch dim).
        self.reset_batch = jax.jit(jax.vmap(self.reset))
        self.step_batch = jax.jit(jax.vmap(self.step))
        self.valid_mask_batch = jax.jit(jax.vmap(self.valid_action_mask))
        self.reset_where_done_jit = jax.jit(self.reset_where_done)
        # Jitted single-game entry points (host GameState wrapper path).
        self.reset_1 = jax.jit(self.reset)
        self.step_1 = jax.jit(self.step)
        self.valid_mask_1 = jax.jit(self.valid_action_mask)

    # --- transition functions (single game; vmap for batches) -------------

    def _slot_placements(self, occupied: jax.Array, shape_idx: jax.Array) -> jax.Array:
        """(R, C) bool of valid origins for one slot's shape.

        Returns all-False for an empty slot (shape_idx < 0).
        """
        sidx = jnp.maximum(shape_idx, 0)
        tr = self._rr[:, :, None] + self._tri_r[sidx][None, None, :]  # (R, C, T)
        tc = self._cc[:, :, None] + self._tri_c[sidx][None, None, :]
        inb = (tr >= 0) & (tr < self.rows) & (tc >= 0) & (tc < self.cols)
        trc = jnp.clip(tr, 0, self.rows - 1)
        tcc = jnp.clip(tc, 0, self.cols - 1)
        free = ~(occupied[trc, tcc] | self._death[trc, tcc])
        parity_ok = ((tr + tc) % 2 == 0) == self._tri_up[sidx][None, None, :]
        ok = (inb & free & parity_ok) | ~self._tri_valid[sidx][None, None, :]
        return ok.all(axis=-1) & (shape_idx >= 0)

    def valid_action_mask(self, state: EnvState) -> jax.Array:
        """(action_dim,) bool; all-False when the game is over."""
        per_slot = jax.vmap(self._slot_placements, in_axes=(None, 0))(
            state.occupied, state.shape_idx
        )  # (SLOTS, R, C)
        return per_slot.reshape(-1) & ~state.done

    def _any_placement(self, occupied: jax.Array, shape_idx: jax.Array) -> jax.Array:
        per_slot = jax.vmap(self._slot_placements, in_axes=(None, 0))(
            occupied, shape_idx
        )
        return per_slot.any()

    def _draw_hand(self, key: jax.Array) -> tuple[jax.Array, jax.Array]:
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (self.num_slots,), 0, self.bank.n_shapes)
        col = jax.random.randint(k2, (self.num_slots,), 0, self.cfg.NUM_COLORS)
        return idx.astype(jnp.int32), col.astype(jnp.int8)

    def reset(self, key: jax.Array) -> EnvState:
        key, sub = jax.random.split(key)
        shape_idx, shape_color = self._draw_hand(sub)
        state = EnvState(
            occupied=jnp.zeros((self.rows, self.cols), dtype=bool),
            color=jnp.full((self.rows, self.cols), -1, dtype=jnp.int8),
            shape_idx=shape_idx,
            shape_color=shape_color,
            score=jnp.float32(0.0),
            step_count=jnp.int32(0),
            done=jnp.bool_(False),
            last_cleared=jnp.int32(0),
            key=key,
        )
        # A fresh board can still be unplayable on exotic configs.
        done = ~self._any_placement(state.occupied, state.shape_idx)
        return state.replace(done=done)

    def step(self, state: EnvState, action: jax.Array) -> tuple[EnvState, jax.Array, jax.Array]:
        """Apply one action. Returns (next_state, reward, done)."""
        cfg = self.cfg
        cells = self.rows * self.cols
        slot = action // cells
        r = (action % cells) // self.cols
        c = action % self.cols

        sidx = jnp.maximum(state.shape_idx[slot], 0)
        placeable = self._slot_placements(state.occupied, state.shape_idx[slot])
        valid = placeable[r, c] & ~state.done

        # --- place ---
        # Padding triangles get an out-of-bounds row so drop-mode scatters
        # ignore them (clipping could alias a real cell and corrupt it).
        tri_on = self._tri_valid[sidx]
        tr = jnp.where(tri_on, r + self._tri_r[sidx], self.rows)
        tc = c + self._tri_c[sidx]
        occ_placed = state.occupied.at[tr, tc].set(True, mode="drop")
        color_placed = state.color.at[tr, tc].set(
            state.shape_color[slot], mode="drop"
        )
        n_placed = self._n_tris[sidx]

        # --- clear full lines ---
        full = (occ_placed | ~self._line_masks).all(axis=(1, 2))  # (L,)
        cleared_cells = (self._line_masks & full[:, None, None]).any(axis=0)
        n_cleared = cleared_cells.sum(dtype=jnp.int32)
        occ_next = occ_placed & ~cleared_cells
        color_next = jnp.where(cleared_cells, jnp.int8(-1), color_placed)

        # --- consume slot; refill when the hand is empty ---
        hand = state.shape_idx.at[slot].set(-1)
        hand_colors = state.shape_color
        all_empty = (hand < 0).all()
        key, sub = jax.random.split(state.key)
        new_idx, new_col = self._draw_hand(sub)
        hand = jnp.where(all_empty, new_idx, hand)
        hand_colors = jnp.where(all_empty, new_col, hand_colors)

        # --- termination: no remaining shape fits ---
        stuck = ~self._any_placement(occ_next, hand)

        gain = (
            n_placed.astype(jnp.float32) * cfg.REWARD_PER_PLACED_TRIANGLE
            + n_cleared.astype(jnp.float32) * cfg.REWARD_PER_CLEARED_TRIANGLE
        )
        reward_valid = gain + jnp.where(stuck, cfg.PENALTY_GAME_OVER, 0.0)

        next_valid = EnvState(
            occupied=occ_next,
            color=color_next,
            shape_idx=hand,
            shape_color=hand_colors,
            score=state.score + gain,
            step_count=state.step_count + 1,
            done=stuck,
            last_cleared=n_cleared,
            key=key,
        )
        # Invalid action on a live game: forfeit (state frozen, game over).
        # Stepping an already-finished game is a true no-op, so last_cleared
        # from the final real move survives.
        next_invalid = state.replace(
            done=jnp.bool_(True),
            last_cleared=jnp.where(state.done, state.last_cleared, jnp.int32(0)),
        )
        reward_invalid = jnp.where(
            state.done, 0.0, jnp.float32(cfg.PENALTY_GAME_OVER)
        )

        next_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(valid, a, b), next_valid, next_invalid
        )
        reward = jnp.where(valid, reward_valid, reward_invalid)
        return next_state, reward.astype(jnp.float32), next_state.done

    def reset_where_done(self, state: EnvState, key: jax.Array) -> EnvState:
        """Batched helper: replace finished games with fresh ones.

        `state` must be batched (leading dim B); `key` is a single key.
        """
        batch = state.done.shape[0]
        fresh = jax.vmap(self.reset)(jax.random.split(key, batch))
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                state.done.reshape((batch,) + (1,) * (old.ndim - 1)), new, old
            ),
            fresh,
            state,
        )
