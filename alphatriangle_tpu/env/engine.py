"""Vectorized triangle-puzzle engine on packed bitboards.

Functional equivalent of the unvendored C++ `trianglengin` engine as
observed through the reference (`alphatriangle/rl/self_play/worker.py:
190-378`, `features/extractor.py:25-66`, `tests/conftest.py:34-41`):
shape slots, placement legality on the up/down triangle lattice with
death cells, simultaneous maximal-line clearing with rewards, hand
refill, and termination when nothing fits.

TPU-first design:
- The (R, C) occupancy grid is packed into `NW = ceil(R*C/32)` uint32
  words (a bitboard). Placement legality is a bitwise AND of the board
  against a precomputed per-(shape, origin) footprint table; line
  clears are word masks + popcount. The engine's hot ops are therefore
  dense 32-bit integer vector ops — no boolean stencil gathers, no
  sub-word layouts, nothing XLA lowers to scalar loops.
- Geometrically impossible placements (out of bounds, parity mismatch,
  death overlap) are folded into the table as a sentinel word that is
  always blocked, so legality needs no separate predicate table.
- Everything is a pure function over an `EnvState` pytree; batching is
  `jax.vmap`, persistence is trivial, and the whole transition fuses
  into the surrounding search/rollout programs under `jit`.
- The color grid (parity API `get_grid_data_np`, reference
  `features/extractor.py:28-31`) stays a dense (R, C) int8 plane — it
  is cold data touched once per step, not per legality probe.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config.env_config import EnvConfig
from .geometry import EnvGeometry, build_geometry
from .shapes import ShapeBank, build_shape_bank


@struct.dataclass
class EnvState:
    """One game's state (add a leading batch dim via vmap)."""

    occupied: jax.Array  # (NW,) uint32 packed occupancy bitboard
    color: jax.Array  # (R, C) int8; -1 where empty
    shape_idx: jax.Array  # (SLOTS,) int32 into the bank; -1 = consumed
    shape_color: jax.Array  # (SLOTS,) int8
    score: jax.Array  # () float32
    step_count: jax.Array  # () int32
    done: jax.Array  # () bool
    last_cleared: jax.Array  # () int32 triangles cleared by the last step
    key: jax.Array  # PRNG key driving shape refills


class _BitTables(NamedTuple):
    """Precomputed bitboard tables (NumPy; uploaded once as constants)."""

    footprint_ext: np.ndarray  # (S, R*C, NW+1) uint32; word NW = blocked flag
    line_words: np.ndarray  # (L, NW) uint32
    death_words: np.ndarray  # (NW,) uint32
    cell_word: np.ndarray  # (R*C,) int32
    cell_bit: np.ndarray  # (R*C,) uint32


def _pack_np(grid: np.ndarray, nw: int) -> np.ndarray:
    """(R, C) bool -> (NW,) uint32 (host-side)."""
    flat = np.asarray(grid, dtype=bool).reshape(-1)
    words = np.zeros(nw, dtype=np.uint32)
    for cell in np.flatnonzero(flat):
        words[cell // 32] |= np.uint32(1) << np.uint32(cell % 32)
    return words


def _build_bit_tables(
    cfg: EnvConfig, bank: ShapeBank, geometry: EnvGeometry
) -> _BitTables:
    rows, cols = cfg.ROWS, cfg.COLS
    cells = rows * cols
    nw = (cells + 31) // 32
    death_flat = geometry.death.reshape(-1)

    fp = np.zeros((bank.n_shapes, cells, nw + 1), dtype=np.uint32)
    for s in range(bank.n_shapes):
        for origin in range(cells):
            r, c = divmod(origin, cols)
            words = np.zeros(nw + 1, dtype=np.uint32)
            ok = True
            for t in range(bank.max_tris):
                if not bank.tri_valid[s, t]:
                    continue
                tr = r + int(bank.tri_r[s, t])
                tc = c + int(bank.tri_c[s, t])
                if not (0 <= tr < rows and 0 <= tc < cols):
                    ok = False
                    break
                # Parity: the cell's up/down-ness must match the
                # shape triangle's (translation must preserve parity).
                if ((tr + tc) % 2 == 0) != bool(bank.tri_up[s, t]):
                    ok = False
                    break
                cell = tr * cols + tc
                if death_flat[cell]:
                    ok = False
                    break
                words[cell // 32] |= np.uint32(1) << np.uint32(cell % 32)
            if not ok:
                # Sentinel: word NW of the board is all-ones, so this
                # placement always collides.
                words[:] = 0
                words[nw] = 1
            fp[s, origin] = words

    line_words = np.stack(
        [_pack_np(m, nw) for m in geometry.line_masks]
    ) if geometry.n_lines else np.zeros((0, nw), np.uint32)

    return _BitTables(
        footprint_ext=fp,
        line_words=line_words,
        death_words=_pack_np(geometry.death, nw),
        cell_word=(np.arange(cells) // 32).astype(np.int32),
        cell_bit=(np.arange(cells) % 32).astype(np.uint32),
    )


class TriangleEnv:
    """Static env: config + precomputed geometry + jitted transition fns.

    Instances are cheap, immutable, and safe to share across threads;
    all mutable state lives in `EnvState` pytrees owned by the caller.
    """

    def __init__(self, cfg: EnvConfig):
        self.cfg = cfg
        self.bank: ShapeBank = build_shape_bank(cfg)
        self.geometry: EnvGeometry = build_geometry(cfg)
        self.rows, self.cols = cfg.ROWS, cfg.COLS
        self.num_slots = cfg.NUM_SHAPE_SLOTS
        self.action_dim = cfg.action_dim
        self.cells = self.rows * self.cols
        self.num_words = (self.cells + 31) // 32

        tables = _build_bit_tables(cfg, self.bank, self.geometry)
        self._tables_np = tables
        # Device-side static tables (XLA embeds these as constants).
        self._fp_ext = jnp.asarray(tables.footprint_ext)
        self._line_words = jnp.asarray(tables.line_words)
        self._cell_word = jnp.asarray(tables.cell_word)
        self._cell_bit = jnp.asarray(tables.cell_bit)
        self._ones_word = jnp.asarray([0xFFFFFFFF], dtype=jnp.uint32)
        self._tri_r = jnp.asarray(self.bank.tri_r)
        self._tri_c = jnp.asarray(self.bank.tri_c)
        self._tri_valid = jnp.asarray(self.bank.tri_valid)
        self._n_tris = jnp.asarray(self.bank.n_tris)

        # Jitted batched entry points (leading batch dim).
        self.reset_batch = jax.jit(jax.vmap(self.reset))
        self.step_batch = jax.jit(jax.vmap(self.step))
        self.valid_mask_batch = jax.jit(jax.vmap(self.valid_action_mask))
        self.reset_where_done_jit = jax.jit(self.reset_where_done)
        # Jitted single-game entry points (host GameState wrapper path).
        self.reset_1 = jax.jit(self.reset)
        self.step_1 = jax.jit(self.step)
        self.valid_mask_1 = jax.jit(self.valid_action_mask)

    # --- bitboard helpers -------------------------------------------------

    def unpack_grid(self, words: jax.Array) -> jax.Array:
        """(NW,) uint32 -> (R, C) bool occupancy grid (traceable)."""
        bits = (words[self._cell_word] >> self._cell_bit) & jnp.uint32(1)
        return (bits > 0).reshape(self.rows, self.cols)

    def unpack_grid_np(self, words: np.ndarray) -> np.ndarray:
        """Host-side twin of `unpack_grid`."""
        t = self._tables_np
        bits = (np.asarray(words)[t.cell_word] >> t.cell_bit) & np.uint32(1)
        return (bits > 0).reshape(self.rows, self.cols)

    def pack_grid_np(self, grid: np.ndarray) -> np.ndarray:
        """(R, C) bool -> (NW,) uint32 (host-side; tests/adapters)."""
        return _pack_np(grid, self.num_words)

    def _or_words(self, words: jax.Array) -> jax.Array:
        """Bitwise-OR reduce over the trailing word axis (static width)."""
        acc = words[..., 0]
        for w in range(1, words.shape[-1]):
            acc = acc | words[..., w]
        return acc

    # --- transition functions (single game; vmap for batches) -------------

    def _legal_per_slot(
        self, occupied: jax.Array, shape_idx: jax.Array
    ) -> jax.Array:
        """(SLOTS, R*C) bool legality of every origin for every slot."""
        sidx = jnp.maximum(shape_idx, 0)
        fp = self._fp_ext[sidx]  # (SLOTS, R*C, NW+1)
        occ_ext = jnp.concatenate([occupied, self._ones_word])  # (NW+1,)
        collide = self._or_words(fp & occ_ext[None, None, :])
        return (collide == 0) & (shape_idx >= 0)[:, None]

    def valid_action_mask(self, state: EnvState) -> jax.Array:
        """(action_dim,) bool; all-False when the game is over."""
        legal = self._legal_per_slot(state.occupied, state.shape_idx)
        return legal.reshape(-1) & ~state.done

    def _any_placement(
        self, occupied: jax.Array, shape_idx: jax.Array
    ) -> jax.Array:
        return self._legal_per_slot(occupied, shape_idx).any()

    def _draw_hand(self, key: jax.Array) -> tuple[jax.Array, jax.Array]:
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (self.num_slots,), 0, self.bank.n_shapes)
        col = jax.random.randint(k2, (self.num_slots,), 0, self.cfg.NUM_COLORS)
        return idx.astype(jnp.int32), col.astype(jnp.int8)

    def reset(self, key: jax.Array) -> EnvState:
        key, sub = jax.random.split(key)
        shape_idx, shape_color = self._draw_hand(sub)
        state = EnvState(
            occupied=jnp.zeros((self.num_words,), dtype=jnp.uint32),
            color=jnp.full((self.rows, self.cols), -1, dtype=jnp.int8),
            shape_idx=shape_idx,
            shape_color=shape_color,
            score=jnp.float32(0.0),
            step_count=jnp.int32(0),
            done=jnp.bool_(False),
            last_cleared=jnp.int32(0),
            key=key,
        )
        # A fresh board can still be unplayable on exotic configs.
        done = ~self._any_placement(state.occupied, state.shape_idx)
        return state.replace(done=done)

    def step(self, state: EnvState, action: jax.Array) -> tuple[EnvState, jax.Array, jax.Array]:
        """Apply one action. Returns (next_state, reward, done)."""
        cfg = self.cfg
        cells = self.cells
        slot = action // cells
        origin = action % cells
        r = origin // self.cols
        c = origin % self.cols

        sidx = jnp.maximum(state.shape_idx[slot], 0)
        fp_ext = self._fp_ext[sidx, origin]  # (NW+1,)
        occ_ext = jnp.concatenate([state.occupied, self._ones_word])
        collide = self._or_words(fp_ext & occ_ext)
        valid = (collide == 0) & (state.shape_idx[slot] >= 0) & ~state.done

        # --- place ---
        fp = fp_ext[: self.num_words]
        occ_placed = state.occupied | fp
        n_placed = self._n_tris[sidx]
        # Color plane (cold parity data): scatter the shape's cells.
        # Padding triangles get an out-of-bounds row so drop-mode
        # scatters ignore them.
        tri_on = self._tri_valid[sidx]
        tr = jnp.where(tri_on, r + self._tri_r[sidx], self.rows)
        tc = c + self._tri_c[sidx]
        color_placed = state.color.at[tr, tc].set(
            state.shape_color[slot], mode="drop"
        )

        # --- clear full lines ---
        miss = (occ_placed[None, :] & self._line_words) ^ self._line_words
        full = self._or_words(miss) == 0 if self._line_words.shape[0] else jnp.zeros((0,), bool)
        masked = jnp.where(
            full[:, None], self._line_words, jnp.uint32(0)
        )
        cleared = (
            self._or_words(jnp.swapaxes(masked, 0, 1))
            if masked.shape[0]
            else jnp.zeros((self.num_words,), jnp.uint32)
        )
        n_cleared = jax.lax.population_count(cleared).sum().astype(jnp.int32)
        occ_next = occ_placed & ~cleared
        cleared_grid = self.unpack_grid(cleared)
        color_next = jnp.where(cleared_grid, jnp.int8(-1), color_placed)

        # --- consume slot; refill when the hand is empty ---
        hand = state.shape_idx.at[slot].set(-1)
        hand_colors = state.shape_color
        all_empty = (hand < 0).all()
        key, sub = jax.random.split(state.key)
        new_idx, new_col = self._draw_hand(sub)
        hand = jnp.where(all_empty, new_idx, hand)
        hand_colors = jnp.where(all_empty, new_col, hand_colors)

        # --- termination: no remaining shape fits ---
        stuck = ~self._any_placement(occ_next, hand)

        gain = (
            n_placed.astype(jnp.float32) * cfg.REWARD_PER_PLACED_TRIANGLE
            + n_cleared.astype(jnp.float32) * cfg.REWARD_PER_CLEARED_TRIANGLE
        )
        reward_valid = gain + jnp.where(stuck, cfg.PENALTY_GAME_OVER, 0.0)

        next_valid = EnvState(
            occupied=occ_next,
            color=color_next,
            shape_idx=hand,
            shape_color=hand_colors,
            score=state.score + gain,
            step_count=state.step_count + 1,
            done=stuck,
            last_cleared=n_cleared,
            key=key,
        )
        # Invalid action on a live game: forfeit (state frozen, game over).
        # Stepping an already-finished game is a true no-op, so last_cleared
        # from the final real move survives.
        next_invalid = state.replace(
            done=jnp.bool_(True),
            last_cleared=jnp.where(state.done, state.last_cleared, jnp.int32(0)),
        )
        reward_invalid = jnp.where(
            state.done, 0.0, jnp.float32(cfg.PENALTY_GAME_OVER)
        )

        next_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(valid, a, b), next_valid, next_invalid
        )
        reward = jnp.where(valid, reward_valid, reward_invalid)
        return next_state, reward.astype(jnp.float32), next_state.done

    def reset_where_done(self, state: EnvState, key: jax.Array) -> EnvState:
        """Batched helper: replace finished games with fresh ones.

        `state` must be batched (leading dim B); `key` is a single key.
        """
        batch = state.done.shape[0]
        fresh = jax.vmap(self.reset)(jax.random.split(key, batch))
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                state.done.reshape((batch,) + (1,) * (old.ndim - 1)), new, old
            ),
            fresh,
            state,
        )
