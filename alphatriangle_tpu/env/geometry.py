"""Static board geometry: death cells, parity, and clearable lines.

The reference's line/clear rules live in the unvendored C++ engine; the
observable contract is that placements fill cells and completed maximal
lines clear (`alphatriangle/rl/self_play/worker.py:377-378` returns
cleared-triangle counts). This module reconstructs that geometry as
precomputed dense masks so the device engine's clear step is one
`(L, R, C)` reduction — no tracing at run time.

Line families on the triangular lattice (cell (r, c) is up iff (r + c)
is even). Each family is the set of cells between two adjacent parallel
lattice lines of one of the three edge orientations:

- horizontal: successor of (r, c) is (r, c + 1);
- diag1 ("\\", down-right strip): successor is (r, c + 1) from an up
  cell and (r + 1, c) from a down cell;
- diag2 ("/", down-left strip): successor is (r, c - 1) from an up cell
  and (r + 1, c) from a down cell.

A *line* is a maximal run of playable cells along one traversal with at
least `LINE_MIN_LENGTH` cells; a line whose cells are all occupied
clears (all full lines clear simultaneously).
"""

from dataclasses import dataclass

import numpy as np

from ..config.env_config import EnvConfig


def build_death_mask(cfg: EnvConfig) -> np.ndarray:
    """(R, C) bool: True where the cell is permanently unplayable."""
    death = np.ones((cfg.ROWS, cfg.COLS), dtype=bool)
    for r, (lo, hi) in enumerate(cfg.PLAYABLE_RANGE_PER_ROW):
        death[r, lo:hi] = False
    return death


def build_up_mask(rows: int, cols: int) -> np.ndarray:
    """(R, C) bool: True where the cell is an up-pointing triangle."""
    rr, cc = np.meshgrid(np.arange(rows), np.arange(cols), indexing="ij")
    return (rr + cc) % 2 == 0


def _successor(family: str, r: int, c: int) -> tuple[int, int]:
    up = (r + c) % 2 == 0
    if family == "horizontal":
        return r, c + 1
    if family == "diag1":
        return (r, c + 1) if up else (r + 1, c)
    if family == "diag2":
        return (r, c - 1) if up else (r + 1, c)
    raise ValueError(family)


def _predecessor(family: str, r: int, c: int) -> tuple[int, int]:
    up = (r + c) % 2 == 0
    if family == "horizontal":
        return r, c - 1
    if family == "diag1":
        # inverse of: up -> (r, c+1) [pred of down], down -> (r+1, c) [pred of up]
        return (r - 1, c) if up else (r, c - 1)
    if family == "diag2":
        return (r - 1, c) if up else (r, c + 1)
    raise ValueError(family)


def build_line_masks(cfg: EnvConfig) -> np.ndarray:
    """(L, R, C) bool masks, one per clearable maximal line.

    Lines are bounded by death cells and board edges; only runs with at
    least LINE_MIN_LENGTH cells are kept. A cell can belong to up to
    three lines (one per family).
    """
    death = build_death_mask(cfg)
    playable = ~death
    rows, cols = cfg.ROWS, cfg.COLS

    def in_bounds(r: int, c: int) -> bool:
        return 0 <= r < rows and 0 <= c < cols

    masks: list[np.ndarray] = []
    for family in ("horizontal", "diag1", "diag2"):
        for r0 in range(rows):
            for c0 in range(cols):
                if not playable[r0, c0]:
                    continue
                pr, pc = _predecessor(family, r0, c0)
                if in_bounds(pr, pc) and playable[pr, pc]:
                    continue  # not a run start
                run: list[tuple[int, int]] = []
                r, c = r0, c0
                while in_bounds(r, c) and playable[r, c]:
                    run.append((r, c))
                    r, c = _successor(family, r, c)
                if len(run) >= cfg.LINE_MIN_LENGTH:
                    m = np.zeros((rows, cols), dtype=bool)
                    for rr, cc in run:
                        m[rr, cc] = True
                    masks.append(m)
    if masks:
        return np.stack(masks)
    return np.zeros((0, rows, cols), dtype=bool)


@dataclass(frozen=True)
class EnvGeometry:
    """All static geometry the engine needs, as dense NumPy arrays."""

    death: np.ndarray  # (R, C) bool
    up: np.ndarray  # (R, C) bool
    line_masks: np.ndarray  # (L, R, C) bool

    @property
    def n_lines(self) -> int:
        return int(self.line_masks.shape[0])

    @property
    def n_playable(self) -> int:
        return int((~self.death).sum())


def build_geometry(cfg: EnvConfig) -> EnvGeometry:
    return EnvGeometry(
        death=build_death_mask(cfg),
        up=build_up_mask(cfg.ROWS, cfg.COLS),
        line_masks=build_line_masks(cfg),
    )
