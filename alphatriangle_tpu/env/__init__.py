"""TPU-native triangle puzzle environment.

Functional equivalent of the reference's C++ `trianglengin` package
(surface reconstructed in SURVEY.md §2b from call sites such as
`alphatriangle/rl/self_play/worker.py:190-377` and
`alphatriangle/features/extractor.py:25-118`) — redesigned as a
struct-of-arrays, jit/vmap-able JAX environment so thousands of games
step in lockstep on the accelerator instead of one C++ object per
Python process.

Public surface:
- `ShapeBank`, `build_shape_bank` — the static library of placeable shapes.
- `EnvGeometry`, `build_geometry` — death mask, parity mask, line masks.
- `TriangleEnv`, `EnvState` — the batched pure-functional engine.
- `GameState`, `Shape` — host-side single-game parity wrapper matching
  the reference `trianglengin.GameState` API.
"""

from .engine import EnvState, TriangleEnv
from .game_state import GameState, Shape
from .geometry import EnvGeometry, build_geometry
from .shapes import ShapeBank, build_shape_bank, enumerate_shapes

__all__ = [
    "EnvGeometry",
    "EnvState",
    "GameState",
    "Shape",
    "ShapeBank",
    "TriangleEnv",
    "build_geometry",
    "build_shape_bank",
    "enumerate_shapes",
]
