"""ASCII rendering of the triangle board and shapes.

The reference ships an interactive pygame UI in its engine package
(`trianglengin play/debug`, reference README.md:199-205); headless
environments get this text twin instead. Up-pointing cells ((r + c)
even) render as ▲/△, down-pointing as ▼/▽; death cells as a dot.
"""

import numpy as np

UP_FULL, UP_EMPTY = "▲", "△"
DOWN_FULL, DOWN_EMPTY = "▼", "▽"
DEATH = "·"


def render_grid(
    occupied: np.ndarray, death: np.ndarray, color: np.ndarray | None = None
) -> str:
    """Multi-line board view with row/column rulers."""
    rows, cols = occupied.shape
    header = "    " + " ".join(f"{c % 10}" for c in range(cols))
    lines = [header]
    for r in range(rows):
        cells = []
        for c in range(cols):
            if death[r, c]:
                cells.append(DEATH)
            elif (r + c) % 2 == 0:
                cells.append(UP_FULL if occupied[r, c] else UP_EMPTY)
            else:
                cells.append(DOWN_FULL if occupied[r, c] else DOWN_EMPTY)
        lines.append(f"{r:>3} " + " ".join(cells))
    return "\n".join(lines)


def render_shape(triangles: list[tuple[int, int, bool]]) -> str:
    """Small standalone picture of one shape."""
    if not triangles:
        return "(empty)"
    min_r = min(t[0] for t in triangles)
    min_c = min(t[1] for t in triangles)
    max_r = max(t[0] for t in triangles)
    max_c = max(t[1] for t in triangles)
    grid = [
        [" "] * (max_c - min_c + 1) for _ in range(max_r - min_r + 1)
    ]
    for r, c, is_up in triangles:
        grid[r - min_r][c - min_c] = UP_FULL if is_up else DOWN_FULL
    return "\n".join(" ".join(row).rstrip() for row in grid)
