"""Shape bank: the static library of placeable triangle shapes.

The reference's engine ships shapes inside the unvendored C++
`trianglengin` package (`Shape.triangles: list[(r, c, is_up)]`,
`Shape.bbox()` — observed at `alphatriangle/features/extractor.py:58-66`).
Here the bank is enumerated deterministically from the config: all
connected triangle polyiamonds with `MIN_SHAPE_TRIANGLES` to
`MAX_SHAPE_TRIANGLES` cells, in fixed orientation, deduplicated under
parity-preserving translation. Both anchor parities are kept as distinct
shapes, which is what makes every physical placement reachable from an
even-parity origin (see `EnvConfig` geometry notes).

The bank is materialized as fixed-shape NumPy arrays (padded to
`MAX_SHAPE_TRIANGLES` triangles) so the device engine can gather shape
geometry with static shapes — no ragged structures reach XLA.
"""

from dataclasses import dataclass, field

import numpy as np

from ..config.env_config import EnvConfig

Cell = tuple[int, int]


def _is_up(r: int, c: int) -> bool:
    """Cell (r, c) is an up-pointing triangle iff (r + c) is even."""
    return (r + c) % 2 == 0


def _neighbors(r: int, c: int) -> list[Cell]:
    """Edge-adjacent cells on the triangular lattice."""
    if _is_up(r, c):
        return [(r, c - 1), (r, c + 1), (r + 1, c)]
    return [(r, c - 1), (r, c + 1), (r - 1, c)]


def _canonicalize(cells: frozenset[Cell]) -> tuple[Cell, ...]:
    """Translate so min row is 0 and min col is 0 or 1, preserving parity.

    A translation by (dr, dc) keeps up/down-ness iff (dr + dc) is even,
    so dc is rounded to keep the shift parity even.
    """
    min_r = min(r for r, _ in cells)
    min_c = min(c for _, c in cells)
    dc = min_c if (min_r + min_c) % 2 == 0 else min_c - 1
    return tuple(sorted((r - min_r, c - dc) for r, c in cells))


def enumerate_shapes(min_tris: int, max_tris: int) -> list[tuple[Cell, ...]]:
    """All fixed-orientation connected shapes with min..max triangles.

    Deterministic: breadth-first growth from the two single-triangle
    seeds, canonicalized each level. Counts follow the fixed polyiamond
    series (2, 3, 6, 14, 36 for sizes 1-5).
    """
    level: set[tuple[Cell, ...]] = {
        _canonicalize(frozenset({(0, 0)})),  # up seed
        _canonicalize(frozenset({(0, 1)})),  # down seed
    }
    out: list[tuple[Cell, ...]] = []
    for size in range(1, max_tris + 1):
        if size >= min_tris:
            out.extend(sorted(level))
        if size == max_tris:
            break
        nxt: set[tuple[Cell, ...]] = set()
        for shape in level:
            cells = set(shape)
            for r, c in shape:
                for nb in _neighbors(r, c):
                    if nb not in cells:
                        nxt.add(_canonicalize(frozenset(cells | {nb})))
        level = nxt
    return out


@dataclass(frozen=True)
class ShapeBank:
    """Dense, padded arrays describing every shape in the bank.

    All arrays have leading dim `n_shapes`; triangle dims are padded to
    `max_tris` with `tri_valid` marking real entries.
    """

    tri_r: np.ndarray  # (S, T) int32 row offsets
    tri_c: np.ndarray  # (S, T) int32 col offsets
    tri_up: np.ndarray  # (S, T) bool: triangle is up-pointing
    tri_valid: np.ndarray  # (S, T) bool: padding mask
    n_tris: np.ndarray  # (S,) int32
    shapes: list[tuple[Cell, ...]] = field(repr=False)  # host-side geometry

    @property
    def n_shapes(self) -> int:
        return int(self.tri_r.shape[0])

    @property
    def max_tris(self) -> int:
        return int(self.tri_r.shape[1])


def bank_shape_triangles(
    bank: ShapeBank, shape_idx: int
) -> list[tuple[int, int, bool]]:
    """One shape's (r, c, is_up) triangle list (reference
    `trianglengin.Shape.triangles` surface)."""
    return [
        (int(r), int(c), _is_up(int(r), int(c)))
        for r, c in bank.shapes[shape_idx]
    ]


def build_shape_bank(cfg: EnvConfig) -> ShapeBank:
    """Enumerate and densify the shape bank for a config."""
    shapes = enumerate_shapes(cfg.MIN_SHAPE_TRIANGLES, cfg.MAX_SHAPE_TRIANGLES)
    if not shapes:
        raise ValueError("shape bank is empty; check MIN/MAX_SHAPE_TRIANGLES")
    s, t = len(shapes), cfg.MAX_SHAPE_TRIANGLES
    tri_r = np.zeros((s, t), dtype=np.int32)
    tri_c = np.zeros((s, t), dtype=np.int32)
    tri_up = np.zeros((s, t), dtype=bool)
    tri_valid = np.zeros((s, t), dtype=bool)
    n_tris = np.zeros(s, dtype=np.int32)
    for i, shape in enumerate(shapes):
        n_tris[i] = len(shape)
        for j, (r, c) in enumerate(shape):
            tri_r[i, j], tri_c[i, j] = r, c
            tri_up[i, j] = _is_up(r, c)
            tri_valid[i, j] = True
    return ShapeBank(
        tri_r=tri_r,
        tri_c=tri_c,
        tri_up=tri_up,
        tri_valid=tri_valid,
        n_tris=n_tris,
        shapes=shapes,
    )
