// Native host-side triangle-puzzle engine (batched bitboard core).
//
// Role: the reference's game engine is a C++ package (`trianglengin`,
// README.md:14,42 of the reference repo); this is our native
// equivalent for HOST-side consumers — interactive play, arena
// evaluation, debugging — where dispatching the jitted JAX engine per
// move wastes milliseconds on dispatch overhead. The DEVICE compute
// path stays pure JAX (env/engine.py); both implementations share the
// exact same precomputed bitboard tables, built once in Python
// (engine._build_bit_tables) and passed in at create time, so the
// transition semantics are identical by construction (pinned by
// tests/test_native_engine.py golden parity tests).
//
// ABI: plain C, batched struct-of-arrays in caller-owned NumPy
// buffers; bound from Python with ctypes (no pybind11 in this image).
//
// Board encoding: the (R, C) occupancy grid packs into NW = ceil(R*C/32)
// uint32 words. Placement legality for (shape s, origin o) is
// `footprint[s][o] & occ_ext == 0` where occ_ext appends one extra
// always-0xFFFFFFFF word and impossible placements store a sentinel bit
// in that word. Line clears are word masks + popcount.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Engine {
  int rows, cols, slots, n_shapes, nw, n_lines, n_colors;
  int cells, action_dim;
  float reward_placed, reward_cleared, penalty_game_over;
  // footprint_ext: n_shapes * cells * (nw + 1) words.
  std::vector<uint32_t> fp;
  // line_words: n_lines * nw words.
  std::vector<uint32_t> lines;

  const uint32_t* fp_row(int shape, int origin) const {
    return fp.data() + (static_cast<size_t>(shape) * cells + origin) * (nw + 1);
  }
};

inline bool fits(const Engine& e, const uint32_t* occ, int shape, int origin) {
  const uint32_t* row = e.fp_row(shape, origin);
  uint32_t collide = row[e.nw];  // sentinel word vs implicit all-ones
  for (int w = 0; w < e.nw; ++w) collide |= row[w] & occ[w];
  return collide == 0;
}

inline bool any_placement(const Engine& e, const uint32_t* occ,
                          const int32_t* hand) {
  for (int s = 0; s < e.slots; ++s) {
    if (hand[s] < 0) continue;
    for (int o = 0; o < e.cells; ++o)
      if (fits(e, occ, hand[s], o)) return true;
  }
  return false;
}

// xorshift64* — deterministic host PRNG for hand refills. (The JAX
// engine draws refills from its threefry key; native trajectories are
// therefore equally-distributed but not bit-identical across the two
// engines once a refill happens — parity tests pin the refill-free
// transition, which is everything except the draw.)
inline uint64_t next_rng(uint64_t& s) {
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return s * 0x2545F4914F6CDD1DULL;
}

}  // namespace

extern "C" {

void* at_create(int rows, int cols, int slots, int n_shapes, int nw,
                int n_lines, int n_colors, float reward_placed,
                float reward_cleared, float penalty_game_over,
                const uint32_t* fp, const uint32_t* lines) {
  Engine* e = new Engine();
  e->rows = rows;
  e->cols = cols;
  e->slots = slots;
  e->n_shapes = n_shapes;
  e->nw = nw;
  e->n_lines = n_lines;
  e->n_colors = n_colors;
  e->cells = rows * cols;
  e->action_dim = slots * e->cells;
  e->reward_placed = reward_placed;
  e->reward_cleared = reward_cleared;
  e->penalty_game_over = penalty_game_over;
  e->fp.assign(fp, fp + static_cast<size_t>(n_shapes) * e->cells * (nw + 1));
  e->lines.assign(lines, lines + static_cast<size_t>(n_lines) * nw);
  return e;
}

void at_destroy(void* ptr) { delete static_cast<Engine*>(ptr); }

// Valid-action masks for n games: out[n * action_dim], 1 = legal.
// All-zero rows for finished games (mirrors valid_action_mask).
void at_valid_mask(const void* ptr, int n, const uint32_t* occ,
                   const int32_t* hand, const uint8_t* done, uint8_t* out) {
  const Engine& e = *static_cast<const Engine*>(ptr);
  for (int g = 0; g < n; ++g) {
    const uint32_t* gocc = occ + static_cast<size_t>(g) * e.nw;
    const int32_t* ghand = hand + static_cast<size_t>(g) * e.slots;
    uint8_t* gout = out + static_cast<size_t>(g) * e.action_dim;
    if (done[g]) {
      std::memset(gout, 0, e.action_dim);
      continue;
    }
    for (int s = 0; s < e.slots; ++s) {
      const bool held = ghand[s] >= 0;
      for (int o = 0; o < e.cells; ++o)
        gout[s * e.cells + o] =
            held && fits(e, gocc, ghand[s], o) ? 1 : 0;
    }
  }
}

// One transition for each of n games (in-place SoA updates). Mirrors
// env/engine.py `step`: placement -> simultaneous full-line clear ->
// slot consume (+ refill when the hand empties and `refill` != 0) ->
// stuck/forfeit termination. Finished games are strict no-ops.
void at_step(const void* ptr, int n, int refill, uint32_t* occ, int8_t* color,
             int32_t* hand, int8_t* hand_color, const int32_t* actions,
             uint64_t* rng, float* rewards, uint8_t* done, float* score,
             int32_t* step_count, int32_t* last_cleared) {
  const Engine& e = *static_cast<const Engine*>(ptr);
  std::vector<uint32_t> cleared(e.nw);
  for (int g = 0; g < n; ++g) {
    uint32_t* gocc = occ + static_cast<size_t>(g) * e.nw;
    int8_t* gcolor = color + static_cast<size_t>(g) * e.cells;
    int32_t* ghand = hand + static_cast<size_t>(g) * e.slots;
    int8_t* ghand_color = hand_color + static_cast<size_t>(g) * e.slots;

    if (done[g]) {  // finished games freeze (lockstep no-op)
      rewards[g] = 0.0f;
      continue;
    }
    const int action = actions[g];
    const int slot = action / e.cells;
    const int origin = action % e.cells;
    const bool in_range = action >= 0 && action < e.action_dim;
    const bool valid =
        in_range && ghand[slot] >= 0 && fits(e, gocc, ghand[slot], origin);
    if (!valid) {  // forfeit: state frozen, game over
      rewards[g] = e.penalty_game_over;
      done[g] = 1;
      last_cleared[g] = 0;
      continue;
    }
    const int shape = ghand[slot];
    const uint32_t* row = e.fp_row(shape, origin);

    // Place: board bits + color plane + triangle count.
    int n_placed = 0;
    for (int w = 0; w < e.nw; ++w) {
      uint32_t bits = row[w];
      gocc[w] |= bits;
      while (bits) {
        const int b = __builtin_ctz(bits);
        bits &= bits - 1;
        gcolor[w * 32 + b] = ghand_color[slot];
        ++n_placed;
      }
    }

    // Clear every simultaneously-full line.
    std::memset(cleared.data(), 0, e.nw * sizeof(uint32_t));
    for (int l = 0; l < e.n_lines; ++l) {
      const uint32_t* line = e.lines.data() + static_cast<size_t>(l) * e.nw;
      bool full = true;
      for (int w = 0; w < e.nw && full; ++w)
        full = (gocc[w] & line[w]) == line[w];
      if (full)
        for (int w = 0; w < e.nw; ++w) cleared[w] |= line[w];
    }
    int n_cleared = 0;
    for (int w = 0; w < e.nw; ++w) {
      n_cleared += __builtin_popcount(cleared[w]);
      gocc[w] &= ~cleared[w];
      uint32_t bits = cleared[w];
      while (bits) {
        const int b = __builtin_ctz(bits);
        bits &= bits - 1;
        gcolor[w * 32 + b] = -1;
      }
    }

    // Consume the slot; refill when the whole hand is empty.
    ghand[slot] = -1;
    bool all_empty = true;
    for (int s = 0; s < e.slots; ++s) all_empty = all_empty && ghand[s] < 0;
    if (all_empty && refill) {
      for (int s = 0; s < e.slots; ++s) {
        ghand[s] = static_cast<int32_t>(next_rng(rng[g]) % e.n_shapes);
        ghand_color[s] =
            static_cast<int8_t>(next_rng(rng[g]) % e.n_colors);
      }
    }

    const float gain = static_cast<float>(n_placed) * e.reward_placed +
                       static_cast<float>(n_cleared) * e.reward_cleared;
    const bool stuck = !any_placement(e, gocc, ghand);
    rewards[g] = gain + (stuck ? e.penalty_game_over : 0.0f);
    score[g] += gain;
    step_count[g] += 1;
    last_cleared[g] = n_cleared;
    done[g] = stuck ? 1 : 0;
  }
}

}  // extern "C"
