"""ctypes bindings + build for the native host engine (engine.cpp).

The shared library is compiled on first use with g++ (no pybind11 in
this image; plain C ABI + ctypes, cached next to the source). The
native engine consumes the SAME bitboard tables the JAX engine builds
(`TriangleEnv._tables_np`), so there is exactly one source of truth
for the game rules' geometry.

Use `native_available()` to probe; consumers must degrade to the JAX
engine when compilation is impossible (no compiler in the deploy
image, read-only filesystem, ...).
"""

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "engine.cpp"
_LIB = Path(__file__).parent / "_libat_engine.so"
_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_build_error: str | None = None

_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i8p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _build() -> "ctypes.CDLL | None":
    global _build_error
    if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return ctypes.CDLL(str(_LIB))
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        str(_SRC), "-o", str(_LIB),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as exc:
        _build_error = f"g++ unavailable: {exc}"
        logger.warning("Native engine build skipped (%s)", _build_error)
        return None
    if proc.returncode != 0:
        _build_error = proc.stderr.strip()[-500:]
        logger.warning("Native engine build failed: %s", _build_error)
        return None
    return ctypes.CDLL(str(_LIB))


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.at_create.restype = ctypes.c_void_p
    lib.at_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_float,
        _u32p, _u32p,
    ]
    lib.at_destroy.argtypes = [ctypes.c_void_p]
    lib.at_valid_mask.argtypes = [
        ctypes.c_void_p, ctypes.c_int, _u32p, _i32p, _u8p, _u8p,
    ]
    lib.at_step.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        _u32p, _i8p, _i32p, _i8p, _i32p, _u64p,
        _f32p, _u8p, _f32p, _i32p, _i32p,
    ]
    return lib


def get_lib() -> "ctypes.CDLL | None":
    """The compiled + bound shared library, or None when unavailable."""
    global _lib
    with _lock:
        if _lib is None and _build_error is None:
            lib = _build()
            if lib is not None:
                _lib = _bind(lib)
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def native_build_error() -> str | None:
    return _build_error


class NativeBatch:
    """Mutable SoA state for N concurrent native games."""

    def __init__(self, engine: "NativeTriangleEnv", n: int, seed: int = 0):
        e = engine
        self.n = n
        self.occupied = np.zeros((n, e.num_words), np.uint32)
        self.color = np.full((n, e.cells), -1, np.int8)
        self.shape_idx = np.full((n, e.num_slots), -1, np.int32)
        self.shape_color = np.zeros((n, e.num_slots), np.int8)
        self.rng = np.random.default_rng(seed).integers(
            1, 2**63, n, dtype=np.uint64
        )
        self.rewards = np.zeros(n, np.float32)
        self.done = np.zeros(n, np.uint8)
        self.score = np.zeros(n, np.float32)
        self.step_count = np.zeros(n, np.int32)
        self.last_cleared = np.zeros(n, np.int32)


class NativeTriangleEnv:
    """Batched host engine sharing the JAX engine's bitboard tables.

    Parity surface mirrors `TriangleEnv.{step,valid_action_mask}`
    semantics on NumPy arrays; refill draws use a host xorshift PRNG
    (equally distributed, not bit-identical to the JAX threefry draws).
    """

    def __init__(self, jax_env):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(
                f"native engine unavailable: {native_build_error()}"
            )
        self._lib = lib
        cfg = jax_env.cfg
        tables = jax_env._tables_np
        self.cfg = cfg
        self.rows, self.cols = cfg.ROWS, cfg.COLS
        self.cells = jax_env.cells
        self.num_words = jax_env.num_words
        self.num_slots = cfg.NUM_SHAPE_SLOTS
        self.action_dim = cfg.action_dim
        self.n_shapes = jax_env.bank.n_shapes
        self._fp = np.ascontiguousarray(
            tables.footprint_ext, dtype=np.uint32
        )
        self._lines = np.ascontiguousarray(tables.line_words, dtype=np.uint32)
        self._handle = lib.at_create(
            self.rows, self.cols, self.num_slots, self.n_shapes,
            self.num_words, self._lines.shape[0], cfg.NUM_COLORS,
            cfg.REWARD_PER_PLACED_TRIANGLE, cfg.REWARD_PER_CLEARED_TRIANGLE,
            cfg.PENALTY_GAME_OVER,
            self._fp.reshape(-1), self._lines.reshape(-1),
        )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.at_destroy(handle)
            self._handle = None

    def new_batch(self, n: int, seed: int = 0) -> NativeBatch:
        """N fresh games with freshly-drawn hands."""
        batch = NativeBatch(self, n, seed)
        self.refill_hands(batch)
        return batch

    def refill_hands(self, batch: NativeBatch, seed: int = 0) -> None:
        """Draw a fresh full hand for every game (host RNG; in-game
        refills after the initial hand happen inside the C engine)."""
        rng = np.random.default_rng((seed, batch.n))
        batch.shape_idx[:] = rng.integers(
            0, self.n_shapes, batch.shape_idx.shape, dtype=np.int32
        )
        batch.shape_color[:] = rng.integers(
            0, self.cfg.NUM_COLORS, batch.shape_color.shape
        ).astype(np.int8)

    def valid_mask(self, batch: NativeBatch) -> np.ndarray:
        out = np.zeros((batch.n, self.action_dim), np.uint8)
        self._lib.at_valid_mask(
            self._handle, batch.n,
            np.ascontiguousarray(batch.occupied),
            np.ascontiguousarray(batch.shape_idx),
            np.ascontiguousarray(batch.done),
            out,
        )
        return out.astype(bool)

    def step(
        self, batch: NativeBatch, actions: np.ndarray, refill: bool = True
    ) -> tuple[np.ndarray, np.ndarray]:
        """Advance every game by one action (in place).

        Returns (rewards, done) views into the batch.
        """
        self._lib.at_step(
            self._handle, batch.n, int(refill),
            batch.occupied, batch.color.reshape(-1),
            batch.shape_idx, batch.shape_color.reshape(-1),
            np.ascontiguousarray(actions, dtype=np.int32), batch.rng,
            batch.rewards, batch.done, batch.score, batch.step_count,
            batch.last_cleared,
        )
        return batch.rewards, batch.done
