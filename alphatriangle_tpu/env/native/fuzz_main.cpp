// Sanitizer fuzz harness for the native engine (SURVEY.md §5: the
// reference has no sanitizer coverage at all; its C++ deps are opaque
// prebuilt wheels. Here the native engine gets an ASAN/UBSan-compiled
// random-playout fuzz run in the test suite).
//
// Built by tests/test_native_engine.py as:
//   g++ -O1 -g -fsanitize=address,undefined -std=c++17 \
//       fuzz_main.cpp engine.cpp -o fuzz && ./fuzz <table_dump>
// The table dump (little-endian header + uint32 tables) is written by
// the test from the SAME Python-built bitboard tables the real engine
// uses, so the fuzz exercises production geometry.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
void* at_create(int rows, int cols, int slots, int n_shapes, int nw,
                int n_lines, int n_colors, float reward_placed,
                float reward_cleared, float penalty_game_over,
                const uint32_t* fp, const uint32_t* lines);
void at_destroy(void* ptr);
void at_valid_mask(const void* ptr, int n, const uint32_t* occ,
                   const int32_t* hand, const uint8_t* done, uint8_t* out);
void at_step(const void* ptr, int n, int refill, uint32_t* occ, int8_t* color,
             int32_t* hand, int8_t* hand_color, const int32_t* actions,
             uint64_t* rng, float* rewards, uint8_t* done, float* score,
             int32_t* step_count, int32_t* last_cleared);
}

static uint64_t rng_state = 0x853c49e6748fea9bULL;
static uint32_t rnd() {
  rng_state ^= rng_state >> 12;
  rng_state ^= rng_state << 25;
  rng_state ^= rng_state >> 27;
  return static_cast<uint32_t>((rng_state * 0x2545F4914F6CDD1DULL) >> 32);
}

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: fuzz <table_dump>\n");
    return 2;
  }
  FILE* f = std::fopen(argv[1], "rb");
  if (!f) {
    std::perror("open");
    return 2;
  }
  int32_t hdr[7];  // rows cols slots n_shapes nw n_lines n_colors
  if (std::fread(hdr, sizeof(int32_t), 7, f) != 7) return 2;
  const int rows = hdr[0], cols = hdr[1], slots = hdr[2], n_shapes = hdr[3],
            nw = hdr[4], n_lines = hdr[5], n_colors = hdr[6];
  const int cells = rows * cols, action_dim = slots * cells;
  std::vector<uint32_t> fp(static_cast<size_t>(n_shapes) * cells * (nw + 1));
  std::vector<uint32_t> lines(static_cast<size_t>(n_lines) * nw);
  if (std::fread(fp.data(), sizeof(uint32_t), fp.size(), f) != fp.size())
    return 2;
  if (n_lines &&
      std::fread(lines.data(), sizeof(uint32_t), lines.size(), f) !=
          lines.size())
    return 2;
  std::fclose(f);

  void* eng = at_create(rows, cols, slots, n_shapes, nw, n_lines, n_colors,
                        1.0f, 2.0f, -10.0f, fp.data(), lines.data());

  const int N = 64, GAMES = 40, MAX_MOVES = 300;
  for (int round_i = 0; round_i < GAMES; ++round_i) {
    std::vector<uint32_t> occ(N * nw, 0);
    std::vector<int8_t> color(N * cells, -1);
    std::vector<int32_t> hand(N * slots);
    std::vector<int8_t> hand_color(N * slots, 0);
    std::vector<uint64_t> rng(N);
    std::vector<float> rewards(N, 0), score(N, 0);
    std::vector<uint8_t> done(N, 0);
    std::vector<int32_t> step_count(N, 0), last_cleared(N, 0);
    std::vector<uint8_t> mask(static_cast<size_t>(N) * action_dim);
    std::vector<int32_t> actions(N);
    for (int g = 0; g < N; ++g) {
      rng[g] = rng_state + g * 977;
      for (int s = 0; s < slots; ++s)
        hand[g * slots + s] = static_cast<int32_t>(rnd() % n_shapes);
    }
    for (int move = 0; move < MAX_MOVES; ++move) {
      at_valid_mask(eng, N, occ.data(), hand.data(), done.data(), mask.data());
      bool all_done = true;
      for (int g = 0; g < N; ++g) {
        if (done[g]) {
          actions[g] = 0;
          continue;
        }
        all_done = false;
        // Mostly-valid actions, occasionally invalid / out-of-range to
        // fuzz the forfeit path.
        const uint32_t dice = rnd() % 100;
        if (dice < 5) {
          actions[g] = static_cast<int32_t>(rnd() % (2 * action_dim)) -
                       action_dim / 2;
          continue;
        }
        const uint8_t* gm = mask.data() + static_cast<size_t>(g) * action_dim;
        int count = 0;
        for (int a2 = 0; a2 < action_dim; ++a2) count += gm[a2];
        if (count == 0) {
          actions[g] = 0;
          continue;
        }
        int pick = static_cast<int>(rnd() % count);
        int chosen = 0;
        for (int a2 = 0; a2 < action_dim; ++a2) {
          if (gm[a2] && pick-- == 0) {
            chosen = a2;
            break;
          }
        }
        actions[g] = chosen;
      }
      if (all_done) break;
      at_step(eng, N, /*refill=*/1, occ.data(), color.data(), hand.data(),
              hand_color.data(), actions.data(), rng.data(), rewards.data(),
              done.data(), score.data(), step_count.data(),
              last_cleared.data());
      // Invariants the sanitizers can't see.
      for (int g = 0; g < N; ++g) {
        if (last_cleared[g] < 0 || last_cleared[g] > cells) {
          std::fprintf(stderr, "bad last_cleared %d\n", last_cleared[g]);
          return 1;
        }
        for (int c2 = 0; c2 < cells; ++c2) {
          const bool occupied =
              (occ[g * nw + c2 / 32] >> (c2 % 32)) & 1u;
          const bool colored = color[g * cells + c2] >= 0;
          if (occupied != colored) {
            std::fprintf(stderr, "occ/color desync at game %d cell %d\n", g,
                         c2);
            return 1;
          }
        }
      }
    }
  }
  at_destroy(eng);
  std::puts("FUZZ_OK");
  return 0;
}
