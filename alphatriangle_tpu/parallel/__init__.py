"""Mesh / sharding utilities (no reference equivalent — SURVEY.md §2c).

The reference's "distributed backend" is Ray RPC with a single-device
learner; here parallelism is expressed as `jax.sharding` over a named
`Mesh` and XLA inserts the ICI collectives.
"""

from .distributed import (
    DistributedConfig,
    initialize_distributed,
    is_primary,
    process_info,
)
from .ring_attention import (
    make_sp_attention,
    ring_attention,
    ulysses_attention,
)
from .sharding import (
    batch_sharding,
    replicated,
    shard_batch,
    state_shardings,
)

__all__ = [
    "DistributedConfig",
    "batch_sharding",
    "initialize_distributed",
    "is_primary",
    "make_sp_attention",
    "process_info",
    "replicated",
    "ring_attention",
    "shard_batch",
    "state_shardings",
    "ulysses_attention",
]
