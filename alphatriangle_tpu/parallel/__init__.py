"""Mesh / sharding utilities (no reference equivalent — SURVEY.md §2c).

The reference's "distributed backend" is Ray RPC with a single-device
learner; here parallelism is expressed as `jax.sharding` over a named
`Mesh` and XLA inserts the ICI collectives.
"""

from .sharding import (
    batch_sharding,
    replicated,
    shard_batch,
    state_shardings,
)

__all__ = ["batch_sharding", "replicated", "shard_batch", "state_shardings"]
