"""Sequence/context-parallel attention: ring and all-to-all (Ulysses).

The reference has no long-context capability at all — its transformer
attends over a <=few-hundred-token spatial sequence on one device
(`alphatriangle/nn/model.py:179-202,283-288`; SURVEY.md §5 "Long-context
/ sequence parallelism: absent"). This module makes sequence length a
*sharding* dimension instead of a ceiling, the TPU-native way:

- **Ring attention** (`ring_attention`): each device on the `sp` mesh
  axis holds a sequence shard of Q, K, V. K/V blocks rotate around the
  ICI ring with `lax.ppermute` while each device folds every block into
  a numerically-stable online softmax (flash-attention style running
  max / normalizer / weighted accumulator). Full bidirectional
  attention is computed without any device ever materializing the
  (S, S) score matrix or the full K/V — memory per device is
  O(S/n * S/n) per block pair, communication is the K/V shards
  streaming over ICI, overlapping compute.
- **Ulysses / all-to-all attention** (`ulysses_attention`): one
  `lax.all_to_all` reshards from sequence-sharded to head-sharded,
  every device computes dense attention over the FULL sequence for its
  head subset, and a second all-to-all reshards back. Cheaper when
  head_count >= sp and the sequence fits one device's HBM; ring wins
  when it doesn't.

Both are pure shard-level functions used inside `shard_map` over the
`MeshConfig` `sp` axis; `make_sp_attention` builds a drop-in
`attention_fn` for `flax.linen.MultiHeadDotProductAttention` (the
model's transformer accepts it via `AlphaTriangleNet.attention_fn`), so
the same network code runs single-device or sequence-sharded with no
change. Equivalence with dense attention (forward and gradients) is
pinned by tests/test_ring_attention.py on the virtual 8-device mesh.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import Array
from jax.sharding import Mesh, PartitionSpec as P


def _fold_block(
    q: Array, k: Array, v: Array, m: Array, l: Array, o: Array, scale: float
) -> tuple[Array, Array, Array]:
    """Fold one K/V block into the online-softmax accumulators.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D).
    m (running max), l (running normalizer): (B, H, Sq) float32.
    o (unnormalized weighted values): (B, Sq, H, D) float32.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    s = s.astype(jnp.float32) * scale
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(-inf - -inf) would be NaN, but m_new is finite whenever any
    # key exists in the block (bidirectional, no masking), and m only
    # equals -inf before the first block where alpha multiplies zeros.
    alpha = jnp.exp(m - m_new)  # (B, H, Sq)
    p = jnp.exp(s - m_new[..., None])  # (B, H, Sq, Sk)
    l = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd",
        p,
        v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    o = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l, o


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis_name: str,
    n_shards: int,
    scale: float | None = None,
) -> Array:
    """Bidirectional ring attention over a sequence-sharded axis.

    Shard-level function (call inside `shard_map`): q, k, v are this
    device's (B, S_local, H, D) sequence shards; the return is the
    (B, S_local, H, D) attention output for the local queries against
    the GLOBAL sequence. K/V rotate `n_shards` hops around the
    `axis_name` ring via `ppermute`; accumulation is float32 online
    softmax regardless of input dtype.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, _ = q.shape
    m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    o = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    # Fold the local block first, then permute-and-fold n_shards-1
    # times: every fold sees the K/V block it needs and the last block
    # is NOT permuted onward afterwards (a trailing ppermute would be
    # pure dead ICI traffic unless XLA happens to DCE it).
    m, l, o = _fold_block(q, k, v, m, l, o, scale)

    def hop(_, carry):
        m, l, o, k, v = carry
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        m, l, o = _fold_block(q, k, v, m, l, o, scale)
        return m, l, o, k, v

    m, l, o, k, v = jax.lax.fori_loop(
        0, n_shards - 1, hop, (m, l, o, k, v), unroll=True
    )
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _dense_attention(q: Array, k: Array, v: Array, scale: float) -> Array:
    """Plain softmax(QK^T)V with float32 accumulation, (B, S, H, D)."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    w = jax.nn.softmax(s.astype(jnp.float32) * scale, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd",
        w,
        v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def ulysses_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis_name: str,
    scale: float | None = None,
) -> Array:
    """All-to-all (Ulysses-style) sequence-parallel attention.

    Shard-level function: reshards (B, S_local, H, D) -> full sequence
    with a head subset (B, S, H_local, D) via one `all_to_all`, runs
    dense attention locally, and reshards back. Requires the head count
    to be divisible by the sp axis size.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)  # (B, S, H_loc, D)
    out = _dense_attention(qh, kh, vh, scale)
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def make_sp_attention(
    mesh: Mesh,
    kind: str = "ring",
    sp_axis: str = "sp",
    dp_axis: str | None = "dp",
):
    """Build a sequence-sharded `attention_fn` for the model's
    transformer (drop-in for `flax.linen.dot_product_attention`).

    Inputs/outputs are global (B, S, H, D) arrays; batch is sharded on
    `dp_axis` (pass None to replicate it) and sequence on `sp_axis`.
    Attention-weight dropout is not supported (like most blockwise
    attention implementations); the caller must be deterministic or use
    zero attention dropout.
    """
    n = mesh.shape[sp_axis]
    spec = P(dp_axis, sp_axis, None, None)
    if kind == "ring":
        inner = functools.partial(
            ring_attention, axis_name=sp_axis, n_shards=n
        )
    elif kind == "ulysses":
        inner = functools.partial(ulysses_attention, axis_name=sp_axis)
    else:
        raise ValueError(f"Unknown sequence-parallel kind: {kind!r}")

    from .sharding import shard_map_compat

    sharded = shard_map_compat(
        lambda q, k, v: inner(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check=False,
    )
    dp_total = mesh.shape[dp_axis] if dp_axis is not None else 1

    # dropout_rate/deterministic MUST be named parameters, not **kwargs:
    # flax's MultiHeadDotProductAttention filters the kwargs it forwards
    # to an attention_fn by inspecting its signature, so a **kwargs
    # catch-all would never receive them and the guard below would be
    # dead code on the real integration path.
    def attention_fn(
        query,
        key,
        value,
        bias=None,
        mask=None,
        dropout_rate=0.0,
        deterministic=True,
        **kwargs,
    ):
        if bias is not None or mask is not None:
            raise NotImplementedError(
                "sequence-parallel attention does not support bias/mask"
            )
        if kind == "ulysses" and query.shape[2] % n:
            raise ValueError(
                f"ulysses attention needs head count ({query.shape[2]}) "
                f"divisible by the sp axis size ({n}); use kind='ring'"
            )
        if dropout_rate and not deterministic:
            raise NotImplementedError(
                "sequence-parallel attention does not support attention-"
                "weight dropout; set ATTENTION_DROPOUT=0 or eval mode"
            )
        b, s = query.shape[0], query.shape[1]
        if b % dp_total or s % n:
            # Shapes that don't tile the mesh (e.g. the batch-1 dummy
            # of model.init) compute densely instead: identical math
            # (equivalence pinned by tests), just not sequence-sharded
            # for this call. Trace-time decision — shapes are static.
            return _dense_attention(
                query, key, value, 1.0 / math.sqrt(query.shape[-1])
            )
        return sharded(query, key, value)

    return attention_fn
