"""Multi-host (DCN) scaffolding: jax.distributed + process-0 gating.

The reference's only cross-node fabric is Ray actor RPC + the plasma
object store (SURVEY.md §2c "Distributed communication backend"); it has
no collectives at all. The TPU-native story: every host joins one
`jax.distributed` cluster, the (dp, mdl) mesh spans all hosts'
devices, and the SAME sharded-jit train step scales from one chip to a
pod — XLA routes gradient reductions over ICI within a host and DCN
across hosts. Host-side singleton work (TensorBoard, checkpoints,
config dumps) runs on process 0 only.

On real TPU pods `jax.distributed.initialize()` auto-discovers the
cluster, so all fields may stay None. For CPU smoke tests (and ad-hoc
clusters) the coordinator/process fields are explicit; see
tests/test_distributed.py for the 2-process harness.
"""

import logging

import jax
from pydantic import BaseModel, Field, model_validator

logger = logging.getLogger(__name__)

_initialized = False


class DistributedConfig(BaseModel):
    """Cluster-membership knobs for `jax.distributed.initialize`."""

    ENABLED: bool = Field(default=False)
    # None = let JAX auto-discover (works on TPU pod slices).
    COORDINATOR_ADDRESS: str | None = Field(default=None)
    NUM_PROCESSES: int | None = Field(default=None, ge=1)
    PROCESS_ID: int | None = Field(default=None, ge=0)

    @model_validator(mode="after")
    def _explicit_fields_come_together(self) -> "DistributedConfig":
        explicit = (self.COORDINATOR_ADDRESS, self.NUM_PROCESSES, self.PROCESS_ID)
        if any(v is not None for v in explicit) and None in explicit:
            raise ValueError(
                "COORDINATOR_ADDRESS, NUM_PROCESSES and PROCESS_ID must be "
                "set together (or all left None for auto-discovery)."
            )
        return self


def initialize_distributed(config: DistributedConfig | None) -> bool:
    """Join the cluster if configured. Idempotent; returns whether this
    process is part of a multi-process run after the call.

    Must run before any JAX backend initializes (i.e. before devices are
    touched), same constraint as `jax.distributed.initialize` itself.
    """
    global _initialized
    if config is None or not config.ENABLED:
        return jax.process_count() > 1
    if _initialized:
        return True
    kwargs = {}
    if config.COORDINATOR_ADDRESS is not None:
        kwargs = {
            "coordinator_address": config.COORDINATOR_ADDRESS,
            "num_processes": config.NUM_PROCESSES,
            "process_id": config.PROCESS_ID,
        }
    jax.distributed.initialize(**kwargs)
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.local_devices()),
        len(jax.devices()),
    )
    return True


def is_primary() -> bool:
    """True on the process that owns singleton host-side work
    (TensorBoard writes, checkpoint saves, config dumps)."""
    return jax.process_index() == 0


def process_info() -> tuple[int, int]:
    """(process_index, process_count)."""
    return jax.process_index(), jax.process_count()
