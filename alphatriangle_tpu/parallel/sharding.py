"""NamedSharding helpers for the (dp, mdl) mesh.

The learner's sharding contract (SURVEY.md §2c "TPU-native equivalent"):
- model/optimizer state is **replicated** across the mesh;
- training batches are **sharded on the dp axis** (leading dim);
- gradients are reduced by XLA-inserted collectives over ICI — the code
  never spells a psum, it falls out of jit over sharded inputs.

Everything here works identically on a real TPU mesh and on the
virtual 8-CPU-device mesh the tests use.
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, opt state, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, dp_axis: str = "dp") -> NamedSharding:
    """Leading-dim sharding over the data-parallel axis."""
    return NamedSharding(mesh, P(dp_axis))


def state_shardings(mesh: Mesh, state) -> object:
    """A pytree of replicated shardings matching `state`'s structure."""
    rep = replicated(mesh)
    return jax.tree_util.tree_map(lambda _: rep, state)


def shard_batch(mesh: Mesh, batch, dp_axis: str = "dp"):
    """Place a host batch pytree onto the mesh, sharded on `dp_axis`.

    Every leaf's leading dimension must be divisible by the dp axis size.
    """
    sh = batch_sharding(mesh, dp_axis)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
