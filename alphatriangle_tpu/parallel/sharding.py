"""NamedSharding helpers for the (dp, mdl) mesh.

The learner's sharding contract (SURVEY.md §2c "TPU-native equivalent"):
- model/optimizer state is **replicated** across the mesh;
- training batches are **sharded on the dp axis** (leading dim);
- gradients are reduced by XLA-inserted collectives over ICI — the code
  never spells a psum, it falls out of jit over sharded inputs.

Everything here works identically on a real TPU mesh and on the
virtual 8-CPU-device mesh the tests use.
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, opt state, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, dp_axis: str = "dp") -> NamedSharding:
    """Leading-dim sharding over the data-parallel axis."""
    return NamedSharding(mesh, P(dp_axis))


def state_shardings(mesh: Mesh, state) -> object:
    """A pytree of replicated shardings matching `state`'s structure."""
    rep = replicated(mesh)
    return jax.tree_util.tree_map(lambda _: rep, state)


def shard_batch(mesh: Mesh, batch, dp_axis: str = "dp"):
    """Place a host batch pytree onto the mesh, sharded on `dp_axis`.

    Single-process: a plain sharded `device_put`; every leaf's leading
    dimension must be divisible by the dp axis size. Multi-process
    (mesh spans hosts): each process passes its LOCAL batch shard and
    the leaves are assembled into global arrays — the global batch is
    the per-process batches concatenated along the leading dim in
    process order.
    """
    sh = batch_sharding(mesh, dp_axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sh, x), batch
        )
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def local_rows(arr, axis: int = 0) -> "np.ndarray":
    """This process's rows of an `axis`-sharded global array.

    Inverse of `shard_batch` for per-sample outputs (e.g. PER TD
    errors): each host gets back exactly the rows it contributed, in
    order, so host-local bookkeeping (priority updates) needs no
    cross-host traffic. Single-process: the whole array. `axis` is the
    batch-sharded dimension (1 for stacked fused-step outputs (K, B)).
    """
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray(arr)
    shards = sorted(
        arr.addressable_shards, key=lambda s: s.index[axis].start or 0
    )
    return np.concatenate([np.asarray(s.data) for s in shards], axis=axis)
