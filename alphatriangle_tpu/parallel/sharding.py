"""NamedSharding helpers for the (dp, mdl, sp) mesh.

The learner's sharding contract (SURVEY.md §2c "TPU-native equivalent"):
- training batches are **sharded on the dp axis** (leading dim);
- model/optimizer state is **replicated** on a 1-wide mdl axis, and
  **tensor-sharded Megatron-style over the mdl axis** when it is wider:
  attention QKV projections and the MLP up-projection split their
  output dimension (column parallel), the attention out-projection and
  MLP down-projection split their input dimension (row parallel), so
  the only cross-shard traffic per layer is the psum after each
  row-parallel matmul — which, like the gradient all-reduce, the code
  never spells: XLA/GSPMD inserts the ICI collectives from the
  shardings alone.

Everything here works identically on a real TPU mesh and on the
virtual 8-CPU-device mesh the tests use.
"""

import logging

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs, check=False):
    """`shard_map` across jax versions.

    Newer jax exposes `jax.shard_map` (validation knob `check_vma`);
    0.4.x only has `jax.experimental.shard_map.shard_map` (knob
    `check_rep`). The computation is identical either way; `check`
    defaults off because the older checker lacks replication rules for
    some primitives these shard functions use (axis_index gathers).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, opt state, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, dp_axis: str = "dp") -> NamedSharding:
    """Leading-dim sharding over the data-parallel axis."""
    return NamedSharding(mesh, P(dp_axis))


# Transformer tensor-parallel layout (Megatron-LM, arXiv:1909.08053):
# per (path-suffix pattern, rank) the PartitionSpec template and which
# dim must divide the mdl axis. Attention kernels are (d, heads, hd)
# for q/k/v and (heads, hd, d) for out — sharding the HEADS dim keeps
# every head intact on one shard, so attention itself needs no
# communication; the out-projection's psum is the layer's only
# collective. MLP: Dense_0 (d, mlp) columns, Dense_1 (mlp, d) rows.
def _tp_spec(path: str, shape: tuple, mdl_axis: str, mdl: int):
    """PartitionSpec for one transformer param leaf, or None (replicate)."""
    if "TransformerEncoderLayer" not in path:
        return None
    if "MultiHeadDotProductAttention" in path:
        for proj in ("query", "key", "value"):
            if f"/{proj}/" in path:
                if path.endswith("kernel") and len(shape) == 3:
                    ok = shape[1] % mdl == 0
                    return P(None, mdl_axis, None) if ok else None
                if path.endswith("bias") and len(shape) == 2:
                    ok = shape[0] % mdl == 0
                    return P(mdl_axis, None) if ok else None
        if "/out/" in path and path.endswith("kernel") and len(shape) == 3:
            ok = shape[0] % mdl == 0
            return P(mdl_axis, None, None) if ok else None
        return None  # out bias, etc.: replicated
    if "/Dense_0/" in path:  # up-projection: column parallel
        if path.endswith("kernel") and len(shape) == 2:
            return P(None, mdl_axis) if shape[1] % mdl == 0 else None
        if path.endswith("bias") and len(shape) == 1:
            return P(mdl_axis) if shape[0] % mdl == 0 else None
    if "/Dense_1/" in path:  # down-projection: row parallel
        if path.endswith("kernel") and len(shape) == 2:
            return P(mdl_axis, None) if shape[0] % mdl == 0 else None
    return None


def state_shardings(
    mesh: Mesh, state, mdl_axis: "str | None" = "mdl"
) -> object:
    """Shardings matching `state`'s structure: tensor-parallel specs
    for transformer params (and their optimizer moments — optax state
    mirrors the params tree, so the same path patterns match) when the
    mesh's mdl axis is wider than 1; replicated otherwise (including
    mdl_axis=None, the no-tensor-parallelism contract)."""
    rep = replicated(mesh)
    mdl = mesh.shape.get(mdl_axis, 1) if mdl_axis is not None else 1
    if mdl <= 1:
        return jax.tree_util.tree_map(lambda _: rep, state)
    logger.info(
        "Tensor parallelism active: transformer params shard over "
        "%s=%d (Megatron layout).",
        mdl_axis,
        mdl,
    )

    def spec_for(path_entries, leaf) -> NamedSharding:
        path = "/".join(
            str(getattr(k, "key", getattr(k, "name", k)))
            for k in path_entries
        )
        spec = _tp_spec(path, tuple(getattr(leaf, "shape", ())), mdl_axis, mdl)
        return NamedSharding(mesh, spec) if spec is not None else rep

    return jax.tree_util.tree_map_with_path(spec_for, state)


def shard_batch(mesh: Mesh, batch, dp_axis: str = "dp"):
    """Place a host batch pytree onto the mesh, sharded on `dp_axis`.

    Single-process: a plain sharded `device_put`; every leaf's leading
    dimension must be divisible by the dp axis size. Multi-process
    (mesh spans hosts): each process passes its LOCAL batch shard and
    the leaves are assembled into global arrays — the global batch is
    the per-process batches concatenated along the leading dim in
    process order.
    """
    sh = batch_sharding(mesh, dp_axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sh, x), batch
        )
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def local_rows(arr, axis: int = 0) -> "np.ndarray":
    """This process's rows of an `axis`-sharded global array.

    Inverse of `shard_batch` for per-sample outputs (e.g. PER TD
    errors): each host gets back exactly the rows it contributed, in
    order, so host-local bookkeeping (priority updates) needs no
    cross-host traffic. Single-process: the whole array. `axis` is the
    batch-sharded dimension (1 for stacked fused-step outputs (K, B)).
    """
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray(arr)
    shards = sorted(
        arr.addressable_shards, key=lambda s: s.index[axis].start or 0
    )
    return np.concatenate([np.asarray(s.data) for s in shards], axis=axis)
