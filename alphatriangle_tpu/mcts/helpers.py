"""Visit-count post-processing (reference
`alphatriangle/rl/self_play/mcts_helpers.py:19-189`).

Dense, batched redesign: the reference converts `dict[int, int]` visit
maps with Python loops; here visit counts are already dense `(B, A)`
arrays out of the batched search, so temperature selection and policy
targets are vectorized jnp ops usable inside jit.
"""

import jax
import jax.numpy as jnp
import numpy as np


class PolicyGenerationError(Exception):
    """Raised when no usable policy can be derived from visit counts
    (reference `mcts_helpers.py:13-16`)."""


def policy_target_from_visits(
    visit_counts: jax.Array, valid_mask: jax.Array | None = None
) -> jax.Array:
    """(..., A) visit counts -> normalized dense policy targets.

    Rows with zero total visits fall back to uniform over valid actions
    (or all actions when no mask is given) instead of NaN.
    """
    counts = jnp.asarray(visit_counts, dtype=jnp.float32)
    total = counts.sum(axis=-1, keepdims=True)
    if valid_mask is not None:
        fallback = valid_mask.astype(jnp.float32)
    else:
        fallback = jnp.ones_like(counts)
    fallback = fallback / jnp.maximum(fallback.sum(axis=-1, keepdims=True), 1.0)
    return jnp.where(total > 0, counts / jnp.maximum(total, 1e-9), fallback)


def select_action_from_visits(
    visit_counts: jax.Array,
    temperature: jax.Array | float,
    rng: jax.Array,
) -> jax.Array:
    """(B, A) visit counts -> (B,) sampled actions.

    Temperature semantics follow the reference (`mcts_helpers.py:19-102`):
    T == 0 -> greedy argmax; T > 0 -> sample ∝ counts^(1/T). Zero-count
    actions are never selected (probability exactly 0); a row with no
    visits at all yields the sentinel -1 (jit cannot raise — callers
    must mask or clamp, e.g. finished games in a batch). `temperature`
    may be a scalar or a per-game (B,) array (move-indexed schedules).
    """
    counts = jnp.asarray(visit_counts, dtype=jnp.float32)
    temp = jnp.broadcast_to(
        jnp.asarray(temperature, dtype=jnp.float32), counts.shape[:-1]
    )[..., None]
    log_counts = jnp.where(counts > 0, jnp.log(counts), -jnp.inf)
    greedy = jnp.argmax(log_counts, axis=-1)
    # Sampling path: logits = log(counts) / T, safe T to avoid /0.
    safe_temp = jnp.maximum(temp, 1e-6)
    gumbel = jax.random.gumbel(rng, counts.shape)
    sampled = jnp.argmax(log_counts / safe_temp + gumbel, axis=-1)
    chosen = jnp.where(temp[..., 0] <= 1e-8, greedy, sampled)
    any_visits = counts.sum(axis=-1) > 0
    return jnp.where(any_visits, chosen, -1).astype(jnp.int32)


def select_root_actions(
    output, use_gumbel: bool = False
) -> np.ndarray:
    """Deterministic (B,) exploitation actions from one SearchOutput.

    PUCT: visit-count argmax (0 for rows with no visits — finished
    games; the engine freezes them, so the action is inert). Gumbel:
    the search's own final-candidate selection (`selected_action`,
    clamped past the -1 sentinel). This is THE action rule arena play,
    `cli eval`, and the serving dispatch share — one definition so the
    three traffic kinds cannot drift apart.
    """
    if use_gumbel:
        return np.maximum(np.asarray(output.selected_action), 0)
    counts = np.asarray(output.visit_counts)
    return np.where(counts.sum(axis=1) > 0, counts.argmax(axis=1), 0)


# --- host-side dict adapters (parity with the reference surface) ----------


def visits_dict_to_dense(
    visits: dict[int, int], action_dim: int
) -> np.ndarray:
    """{action: count} -> dense (A,) float32 counts."""
    dense = np.zeros(action_dim, dtype=np.float32)
    for a, c in visits.items():
        if not 0 <= a < action_dim:
            raise PolicyGenerationError(
                f"Visit action {a} outside action space [0, {action_dim})."
            )
        dense[a] = c
    return dense


def select_action_from_visits_dict(
    visits: dict[int, int],
    action_dim: int,
    temperature: float,
    seed: int = 0,
) -> int:
    """Reference-shaped single-game selection over a visit dict."""
    if not visits or sum(visits.values()) <= 0:
        raise PolicyGenerationError("No visits to select an action from.")
    dense = visits_dict_to_dense(visits, action_dim)
    action = select_action_from_visits(
        dense[None], temperature, jax.random.PRNGKey(seed)
    )[0]
    return int(action)
