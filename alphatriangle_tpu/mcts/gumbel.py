"""Gumbel root search with sequential halving (beyond-reference).

Implements the root-action procedure of "Policy improvement by
planning with Gumbel" (Danihelka et al., ICLR 2022; the mctx
`gumbel_muzero_policy`) on top of the wave-parallel batched search:

- Root exploration comes from sampled Gumbel noise on the prior
  logits, NOT Dirichlet noise + visit-count temperature: the m
  highest `g(a) + logits(a)` valid actions become the candidate set.
- **Sequential halving rides the wave structure**: each wave spreads
  its W simulations evenly over the surviving candidates, and after
  every wave the candidate set is halved by
  `g + logits + sigma(qhat)` score — so the number of waves IS the
  number of halving phases, and the whole schedule stays static
  shapes (a (B, A) candidate mask carried through `lax.fori_loop`).
- The played action is the argmax of the final candidates' scores
  (exploration is entirely the Gumbel sample — no temperature), and
  the policy target is the **completed-Q improved policy**
  `softmax(logits + sigma(q_completed))` over valid actions, where
  unvisited actions take the root's network value (a simplification
  of mctx's prior-weighted value mix, documented here).

This beats visit-count PUCT targets at small simulation budgets
because every simulation is spent comparing the few root actions that
matter, and the improved policy is a proper policy-improvement
operator rather than a visit histogram. Enable with
`MCTSConfig.root_selection="gumbel"`.

sigma(q) = (c_visit + max_a N(a)) * c_scale * q  (paper Eq. 8 defaults).
"""

import jax
import jax.numpy as jnp

from ..config.mcts_config import MCTSConfig
from ..telemetry.device_stats import beacon_every, emit_beacon
from .search import BatchedMCTS, SearchOutput


class GumbelMCTS(BatchedMCTS):
    """Wave-parallel search with Gumbel sequential-halving root."""

    def __init__(
        self,
        env,
        extractor,
        model,
        config: MCTSConfig,
        support,
        exploit: bool = False,
    ):
        # Dirichlet root noise is PUCT's exploration mechanism; Gumbel
        # sampling replaces it entirely (paper §3). `exploit` zeroes
        # the Gumbel sample too (deterministic logits + sigma(q)
        # halving/argmax) — playout-cap fast searches must play the
        # best cheap move, not explore.
        super().__init__(
            env,
            extractor,
            model,
            config.model_copy(update={"dirichlet_epsilon": 0.0}),
            support,
        )
        self.m_candidates = config.gumbel_m
        self.c_visit = config.gumbel_c_visit
        self.c_scale = config.gumbel_c_scale
        self.exploit = exploit

    # --- scoring helpers --------------------------------------------------

    def _sigma(self, q: jax.Array, visit_counts: jax.Array) -> jax.Array:
        """Monotone Q transform: (c_visit + max N) * c_scale * q."""
        max_n = visit_counts.max(axis=-1, keepdims=True)
        return (self.c_visit + max_n) * self.c_scale * q

    def _root_q(self, tree) -> tuple[jax.Array, jax.Array]:
        """(q, visits) of the root edges, (B, A) each."""
        visits = tree.e_visits[:, 0, :]
        q = jnp.where(
            visits > 0, tree.e_value[:, 0, :] / jnp.maximum(visits, 1e-9), 0.0
        )
        return q, visits

    # --- the search -------------------------------------------------------

    def _search(self, variables, root_states, rng: jax.Array) -> SearchOutput:
        cfg = self.config
        batch = root_states.done.shape[0]
        a = self.action_dim
        w = self.wave_size
        # Distinct keys for root init and the Gumbel sample: reusing
        # one is harmless only while GumbelMCTS forces
        # dirichlet_epsilon=0 (init never consumes its key); a fourth
        # key keeps root noise and Gumbel perturbations independent if
        # Dirichlet were ever re-enabled.
        rng, init_rng, gumbel_rng, wave_rng = jax.random.split(rng, 4)
        tree = self._init_tree(variables, root_states, init_rng)

        valid = tree.valid[:, 0, :] > 0  # (B, A)
        logits = jnp.where(
            valid, jnp.log(jnp.maximum(tree.prior[:, 0, :], 1e-12)), -jnp.inf
        )
        g = (
            jnp.zeros((batch, a))
            if self.exploit
            else jax.random.gumbel(gumbel_rng, (batch, a))
        )
        base_score = jnp.where(valid, g + logits, -jnp.inf)  # (B, A)

        # Initial candidates: top-m by g + logits among valid actions.
        # m is clamped to the wave size so EVERY survivor receives at
        # least one simulation per halving phase — otherwise arms could
        # be halved (or even played) on sigma(q)=0 without ever being
        # simulated.
        m0 = min(self.m_candidates, w, a)
        kth = jnp.sort(base_score, axis=-1)[:, -m0][:, None]
        cand = valid & (base_score >= kth)  # (B, A) may hold < m0 rows

        def assign_roots(tree, cand_mask: jax.Array) -> jax.Array:
            """(B, A) mask -> (B, W) member root actions.

            The first `count` members cover every surviving candidate
            once; surplus members repeat the cycle ONLY onto already-
            expanded candidates (their descent then deepens that
            subtree via PUCT). A surplus member aimed at a still-
            unexpanded edge would duplicate the first member's
            expansion wholesale, so it is released (-1 = unforced) to
            a noise-diversified PUCT descent instead.
            """
            order = jnp.argsort(~cand_mask, axis=-1, stable=True)  # (B, A)
            count = jnp.maximum(cand_mask.sum(axis=-1, keepdims=True), 1)
            j = jnp.arange(w)[None, :]  # (1, W)
            slot = j % count  # (B, W)
            roots = jnp.take_along_axis(order, slot, axis=1).astype(
                jnp.int32
            )
            expanded = (
                jnp.take_along_axis(tree.children[:, 0, :], roots, axis=1)
                >= 0
            )
            force = (j < count) | expanded
            return jnp.where(force, roots, -1)

        def halve(tree, cand_mask: jax.Array) -> jax.Array:
            """Keep the better half of the candidates by g+logits+sigma(q)."""
            q, visits = self._root_q(tree)
            score = jnp.where(
                cand_mask, base_score + self._sigma(q, visits), -jnp.inf
            )
            count = cand_mask.sum(axis=-1)
            keep = jnp.maximum((count + 1) // 2, 1)  # ceil(count/2), >= 1
            sorted_scores = jnp.sort(score, axis=-1)  # ascending
            kth = jnp.take_along_axis(
                sorted_scores, (a - keep)[:, None], axis=1
            )
            return cand_mask & (score >= kth)

        def wave_body(k, carry):
            # The search carry is (tree, wasted, base) plus the
            # device-stats histogram tail when enabled (`_stats_seed`);
            # the candidate mask rides behind it and never enters
            # `_wave`.
            *sc, cand_mask = carry
            emit_beacon("search_wave", k, every=beacon_every())
            roots = assign_roots(sc[0], cand_mask)
            sc = self._wave(
                variables,
                batch,
                tuple(sc),
                jax.random.fold_in(wave_rng, k),
                root_action=roots,
            )
            tree = sc[0]
            # Halve after every wave but the last (the final set is
            # resolved by argmax below).
            cand_mask = jax.lax.cond(
                k < self.num_waves - 1,
                lambda: halve(tree, cand_mask),
                lambda: cand_mask,
            )
            return (*sc, cand_mask)

        final = jax.lax.fori_loop(
            0,
            self.num_waves,
            wave_body,
            (tree, jnp.zeros((batch,), jnp.int32), jnp.int32(1))
            + self._stats_seed()
            + (cand,),
        )
        tree, wasted, base = final[0], final[1], final[2]
        stats_tail, cand = final[3:-1], final[-1]

        q, visits = self._root_q(tree)
        final_score = jnp.where(
            cand, base_score + self._sigma(q, visits), -jnp.inf
        )
        selected = jnp.argmax(final_score, axis=-1).astype(jnp.int32)
        # Terminal roots have no meaningful selection; mirror PUCT's
        # no-visit sentinel so the host-side guard logic stays shared.
        selected = jnp.where(root_states.done, -1, selected)

        # Completed-Q improved policy (paper §4): unvisited actions
        # take the root network value (simplified value mix).
        q_completed = jnp.where(visits > 0, q, tree.root_value0[:, None])
        improved_logits = jnp.where(
            valid, logits + self._sigma(q_completed, visits), -jnp.inf
        )
        any_valid = valid.any(axis=-1, keepdims=True)
        improved = jax.nn.softmax(
            jnp.where(any_valid, improved_logits, 0.0), axis=-1
        )
        improved = jnp.where(valid, improved, 0.0)
        norm = improved.sum(axis=-1, keepdims=True)
        improved = improved / jnp.maximum(norm, 1e-9)

        root_visits = 1.0 + visits.sum(axis=-1)
        root_value = (
            tree.root_value0 + tree.e_value[:, 0, :].sum(axis=-1)
        ) / root_visits
        stats = None
        if self.device_stats:
            stats = self._stat_pack(tree, wasted, base, stats_tail[0], batch)
        return SearchOutput(
            visit_counts=visits,
            root_value=root_value,
            root_prior=tree.prior[:, 0],
            total_simulations=jnp.int32(cfg.max_simulations * batch),
            wasted_slots=wasted,
            selected_action=selected,
            improved_policy=improved,
            stats=stats,
        )
