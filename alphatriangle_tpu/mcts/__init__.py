"""Batched AlphaZero MCTS (trimcts equivalent, SURVEY.md §2b).

The reference's C++ search walks one tree per worker process and ships
`mcts_batch_size=32` leaves at a time back into Python for CPU net
evaluation. Here the search itself is a jitted JAX program over fixed
shape tree-of-arrays state: B games search in lockstep and every
simulation evaluates all B leaves in ONE batched network call on the
MXU — the architectural change BASELINE.md names as the games/hour
make-or-break.
"""

from .gumbel import GumbelMCTS
from .helpers import (
    PolicyGenerationError,
    policy_target_from_visits,
    select_action_from_visits,
    select_root_actions,
)
from .search import BatchedMCTS, SearchOutput

__all__ = [
    "BatchedMCTS",
    "GumbelMCTS",
    "PolicyGenerationError",
    "SearchOutput",
    "policy_target_from_visits",
    "select_action_from_visits",
    "select_root_actions",
]
