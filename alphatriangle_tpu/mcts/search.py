"""Batched PUCT search over fixed-shape tree arrays.

Functional equivalent of the observed trimcts surface
(`alphatriangle/config/mcts_config.py:67-77`,
`alphatriangle/rl/self_play/worker.py:273-280`): PUCT selection with
cpuct, Dirichlet root noise, max-depth cutoff, discounted value backup,
dense visit-count extraction.

TPU-first design, not a translation of the C++ pointer tree:
- A search over B games is ONE jitted computation. Tree state is a
  struct-of-arrays pytree with leading dims (B, N) where
  N = max_simulations + 1 node slots (root + one expansion per sim).
- Each simulation does: vmapped PUCT descent (bounded `lax.while_loop`)
  -> one batched env.step for all B selected edges -> one batched
  feature-extract + network apply for all B new leaves (the MXU call)
  -> vmapped discounted backup along parent chains.
- All shapes static; no Python control flow inside jit.
- Terminal nodes evaluate to value 0 and step as no-ops (the engine
  freezes finished games), so finished games in a batch stay in
  lockstep at zero extra cost.
- Subtree reuse (the reference's opaque tree handle) is intentionally
  absent: with B games searched per dispatch, re-searching from the
  root each move keeps shapes static and the MXU saturated; the
  root-prior already encodes the network's (fresher) knowledge.
"""

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from ..config.mcts_config import MCTSConfig
from ..env.engine import EnvState, TriangleEnv
from ..features.core import FeatureExtractor


@struct.dataclass
class Tree:
    """Search-tree arrays for one game (batched: add a leading B dim)."""

    node_state: EnvState  # (N, ...) game state at each node
    visits: jax.Array  # (N,) int32
    value_sum: jax.Array  # (N,) float32 sum of backed-up returns
    prior: jax.Array  # (N, A) float32 masked policy priors
    valid: jax.Array  # (N, A) bool valid-action masks
    children: jax.Array  # (N, A) int32 child node index; -1 = unexpanded
    parent: jax.Array  # (N,) int32; -1 at root
    parent_action: jax.Array  # (N,) int32; -1 at root
    reward: jax.Array  # (N,) float32 reward on the edge into this node
    terminal: jax.Array  # (N,) bool


@struct.dataclass
class SearchOutput:
    """Result of one batched search."""

    visit_counts: jax.Array  # (B, A) float32 root child visit counts
    root_value: jax.Array  # (B,) float32 mean backed-up root value
    root_prior: jax.Array  # (B, A) float32 noisy root prior (debug)
    total_simulations: jax.Array  # () int32


class BatchedMCTS:
    """PUCT search bound to (env, features, model); `search` is jitted.

    `evaluate` contract: the Flax model applied to extracted features,
    returning (policy_logits, value_logits -> scalar values) — the same
    role as the reference's `AlphaZeroNetworkInterface.evaluate_batch`
    (`alphatriangle/nn/network.py:242-318`) but traced into the search.
    """

    def __init__(
        self,
        env: TriangleEnv,
        extractor: FeatureExtractor,
        model: Any,
        config: MCTSConfig,
        value_support: jax.Array,
    ):
        self.env = env
        self.extractor = extractor
        self.model = model
        self.config = config
        self.support = value_support
        self.num_nodes = config.max_simulations + 1
        self.action_dim = env.action_dim
        self.search = jax.jit(self._search)

    # --- network evaluation ----------------------------------------------

    def _evaluate(self, variables, states: EnvState):
        """Batched leaf eval: states (B-leading) -> (priors (B,A), values (B,)).

        Priors are masked to valid actions and renormalized (uniform over
        valid when the network mass on valid actions vanishes — the
        reference's fallback, `nn/network.py:200-215`).
        """
        grids, others = jax.vmap(self.extractor.extract)(states)
        policy_logits, value_logits = self.model.apply(
            variables, grids, others, train=False
        )
        valid = jax.vmap(self.env.valid_action_mask)(states)  # (B, A)
        masked_logits = jnp.where(valid, policy_logits, -jnp.inf)
        # Softmax over valid actions only; all-invalid rows -> zeros.
        any_valid = valid.any(axis=-1, keepdims=True)
        safe_logits = jnp.where(any_valid, masked_logits, 0.0)
        priors = jax.nn.softmax(safe_logits, axis=-1)
        priors = jnp.where(valid, priors, 0.0)
        norm = priors.sum(axis=-1, keepdims=True)
        uniform = valid.astype(jnp.float32) / jnp.maximum(
            valid.sum(axis=-1, keepdims=True), 1
        )
        priors = jnp.where(norm > 1e-9, priors / jnp.maximum(norm, 1e-9), uniform)
        value_probs = jax.nn.softmax(value_logits, axis=-1)
        values = jnp.sum(value_probs * self.support, axis=-1)
        return priors, values, valid

    # --- per-tree primitives (single game; vmapped) -----------------------

    def _puct_scores(self, tree: Tree, node: jax.Array) -> jax.Array:
        """(A,) PUCT score of each action at `node`."""
        cfg = self.config
        child = tree.children[node]  # (A,)
        cidx = jnp.maximum(child, 0)
        expanded = child >= 0
        c_visits = jnp.where(expanded, tree.visits[cidx], 0)
        c_value = jnp.where(
            c_visits > 0, tree.value_sum[cidx] / jnp.maximum(c_visits, 1), 0.0
        )
        q = jnp.where(
            expanded, tree.reward[cidx] + cfg.discount * c_value, 0.0
        )
        u = (
            cfg.cpuct
            * tree.prior[node]
            * jnp.sqrt(tree.visits[node].astype(jnp.float32))
            / (1.0 + c_visits.astype(jnp.float32))
        )
        return jnp.where(tree.valid[node], q + u, -jnp.inf)

    def _select_leaf(self, tree: Tree) -> tuple[jax.Array, jax.Array]:
        """Descend by PUCT until an unexpanded edge / depth cap / terminal.

        Returns (parent node index, action to expand).
        """
        max_depth = self.config.max_depth

        def cond(carry):
            _, _, _, stop = carry
            return ~stop

        def body(carry):
            node, _, depth, _ = carry
            action = jnp.argmax(self._puct_scores(tree, node))
            child = tree.children[node, action]
            stop = (
                (child < 0)
                | (depth + 1 >= max_depth)
                | tree.terminal[node]
            )
            next_node = jnp.where(stop, node, child)
            return next_node, action, depth + 1, stop

        node, action, _, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.bool_(False))
        )
        return node, action

    def _backup(
        self, tree: Tree, leaf: jax.Array, leaf_value: jax.Array
    ) -> tuple[jax.Array, jax.Array]:
        """Discounted backup from `leaf` to root; returns updated
        (visits, value_sum)."""
        discount = self.config.discount

        def cond(carry):
            node, *_ = carry
            return node >= 0

        def body(carry):
            # Under vmap, lanes that already reached the root keep
            # executing this body while other lanes walk; guard every
            # update so a finished lane (node == -1) is a strict no-op
            # instead of wrap-indexing the last slot.
            node, g, visits, value_sum = carry
            active = node >= 0
            safe = jnp.maximum(node, 0)
            visits = visits.at[safe].add(jnp.where(active, 1, 0))
            value_sum = value_sum.at[safe].add(jnp.where(active, g, 0.0))
            g = jnp.where(active, tree.reward[safe] + discount * g, g)
            node = jnp.where(active, tree.parent[safe], node)
            return node, g, visits, value_sum

        _, _, visits, value_sum = jax.lax.while_loop(
            cond, body, (leaf, leaf_value, tree.visits, tree.value_sum)
        )
        return visits, value_sum

    # --- the search -------------------------------------------------------

    def _init_tree(self, variables, root_states: EnvState, rng) -> Tree:
        """Batched tree init: root eval + Dirichlet noise."""
        cfg = self.config
        batch = root_states.done.shape[0]
        n, a = self.num_nodes, self.action_dim

        priors, values, valid = self._evaluate(variables, root_states)
        root_terminal = root_states.done
        root_value = jnp.where(root_terminal, 0.0, values)

        # Dirichlet root noise over valid actions (eps=0 or alpha=0 -> off).
        if cfg.dirichlet_epsilon > 0 and cfg.dirichlet_alpha > 0:
            gammas = jax.random.gamma(
                rng, cfg.dirichlet_alpha, shape=(batch, a)
            )
            gammas = jnp.where(valid, gammas, 0.0)
            noise = gammas / jnp.maximum(
                gammas.sum(axis=-1, keepdims=True), 1e-9
            )
            priors = (1.0 - cfg.dirichlet_epsilon) * priors + (
                cfg.dirichlet_epsilon
            ) * noise
            priors = jnp.where(valid, priors, 0.0)

        def broadcast_to_nodes(x):
            """Tile each game's root state across its N node slots."""
            return jnp.broadcast_to(x[:, None], (batch, n) + x.shape[1:])

        node_state = jax.tree_util.tree_map(broadcast_to_nodes, root_states)
        zeros_na = jnp.zeros((batch, n, a), dtype=jnp.float32)
        tree = Tree(
            node_state=node_state,
            visits=jnp.zeros((batch, n), dtype=jnp.int32).at[:, 0].set(1),
            value_sum=jnp.zeros((batch, n), dtype=jnp.float32)
            .at[:, 0]
            .set(root_value),
            prior=zeros_na.at[:, 0].set(priors),
            valid=jnp.zeros((batch, n, a), dtype=bool).at[:, 0].set(valid),
            children=jnp.full((batch, n, a), -1, dtype=jnp.int32),
            parent=jnp.full((batch, n), -1, dtype=jnp.int32),
            parent_action=jnp.full((batch, n), -1, dtype=jnp.int32),
            reward=jnp.zeros((batch, n), dtype=jnp.float32),
            terminal=jnp.zeros((batch, n), dtype=bool).at[:, 0].set(root_terminal),
        )
        return tree

    def _search(
        self, variables, root_states: EnvState, rng: jax.Array
    ) -> SearchOutput:
        """Run `max_simulations` batched simulations from `root_states`."""
        cfg = self.config
        batch = root_states.done.shape[0]
        rng, noise_rng = jax.random.split(rng)
        tree = self._init_tree(variables, root_states, noise_rng)
        barange = jnp.arange(batch)

        def sim_body(sim: jax.Array, tree: Tree) -> Tree:
            # 1. Selection: vmapped descent over all B trees. The
            # returned edge may already be expanded when the descent was
            # stopped by the depth cap or a terminal node.
            parents, actions = jax.vmap(self._select_leaf)(tree)
            existing = tree.children[barange, parents, actions]  # (B,)
            is_new = existing < 0

            # 2. Expansion: one batched env.step over the selected edges.
            # (The engine is deterministic given the node's PRNG state,
            # so a revisited edge reproduces the existing child's state.)
            parent_states = jax.tree_util.tree_map(
                lambda x: x[barange, parents], tree.node_state
            )
            new_states, rewards, dones = jax.vmap(self.env.step)(
                parent_states, actions
            )

            # 3. Evaluation: ONE batched network call for all B leaves.
            priors, values, valid = self._evaluate(variables, new_states)
            leaf_values = jnp.where(dones, 0.0, values)

            # 4. Insert node `sim`. For revisited edges the existing
            # child keeps the edge (and its accumulated statistics);
            # slot `sim` is then an orphan with zero visits — a bounded
            # waste that keeps every shape static.
            node = sim  # scalar; same slot in every tree
            target = jnp.where(is_new, node, existing)  # (B,) backup roots
            ns = jax.tree_util.tree_map(
                lambda buf, x: buf.at[:, node].set(x),
                tree.node_state,
                new_states,
            )
            tree = tree.replace(
                node_state=ns,
                prior=tree.prior.at[:, node].set(priors),
                valid=tree.valid.at[:, node].set(valid),
                children=tree.children.at[barange, parents, actions].set(
                    target
                ),
                parent=tree.parent.at[:, node].set(
                    jnp.where(is_new, parents, -1)
                ),
                parent_action=tree.parent_action.at[:, node].set(
                    jnp.where(is_new, actions, -1)
                ),
                reward=tree.reward.at[:, node].set(rewards),
                terminal=tree.terminal.at[:, node].set(dones),
            )

            # 5. Backup: vmapped discounted walk to the root, starting
            # from the (possibly pre-existing) child of the chosen edge.
            visits, value_sum = jax.vmap(self._backup)(
                tree, target, leaf_values
            )
            return tree.replace(visits=visits, value_sum=value_sum)

        tree = jax.lax.fori_loop(1, cfg.max_simulations + 1, sim_body, tree)

        # Root visit counts: scatter child visits by parent_action for
        # nodes whose parent is the root.
        def root_counts(tree_i: Tree) -> jax.Array:
            is_root_child = tree_i.parent == 0
            counts = jnp.zeros(self.action_dim, dtype=jnp.float32)
            return counts.at[
                jnp.maximum(tree_i.parent_action, 0)
            ].add(jnp.where(is_root_child, tree_i.visits, 0).astype(jnp.float32))

        visit_counts = jax.vmap(root_counts)(tree)
        root_value = tree.value_sum[:, 0] / jnp.maximum(
            tree.visits[:, 0].astype(jnp.float32), 1.0
        )
        return SearchOutput(
            visit_counts=visit_counts,
            root_value=root_value,
            root_prior=tree.prior[:, 0],
            total_simulations=jnp.int32(cfg.max_simulations * batch),
        )
