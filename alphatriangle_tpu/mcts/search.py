"""Batched wave-parallel PUCT search as dense MXU linear algebra.

Functional equivalent of the observed trimcts surface
(`alphatriangle/config/mcts_config.py:67-77`,
`alphatriangle/rl/self_play/worker.py:273-280`): PUCT selection with
cpuct, Dirichlet root noise, max-depth cutoff, discounted value backup,
dense visit-count extraction, and batched leaf collection (the
reference's `mcts_batch_size` C++ leaf batching,
`mcts_config.py:57-62`).

TPU-first design, not a translation of the C++ pointer tree:
- A search over B games is ONE jitted computation. Tree statistics are
  **edge-indexed** struct-of-arrays with dims (B, N, A): visit counts,
  return sums, rewards, priors, validity, and child ids all live on
  edges (node x action), so everything PUCT needs at a node is one
  contiguous row — never a per-action pointer chase.
- Simulations run in **waves of W members** (W = `mcts_batch_size`
  clamped to a divisor of max_simulations). Each wave:
    1. W parallel PUCT descents per tree, a static `fori_loop` over
       max_depth levels. Each level reads its tree rows with ONE
       batched one-hot matmul `(B,W,N) x (B,N,6A)` against a per-wave
       concatenation of the six stat planes — an MXU contraction, not
       a gather. Descents are diversified by per-member Gumbel
       perturbation (`wave_noise_scale`) instead of sequential
       virtual loss, and record their (node, action, reward) path.
    2. one batched env.step over the B*W selected edges (bitboards);
    3. ONE fused network evaluation of all B*W leaves;
    4. block insertion of the W new node slots via dynamic-slice
       updates; within-wave duplicate edges are canonicalized to a
       single child (duplicates and re-expanded edges become orphan
       slots, counted in `wasted_slots`);
    5. discounted backup along the recorded paths: max_depth static
       rounds of (B, W)-sized scatter-adds into the edge planes — no
       data-dependent `while` walk, no parent pointers.
- All shapes static; no Python control flow inside jit. Sequential
  dispatch rounds per search scale with (sims/W) * max_depth, and the
  per-round work is dense f32 vector/matrix math.
- Terminal nodes evaluate to value 0 and step as no-ops (the engine
  freezes finished games), so finished games in a batch stay in
  lockstep at zero extra cost.
- Subtree reuse (the reference's opaque tree handle) is OFF by
  default and static-shape when on (`MCTSConfig.tree_reuse`): the
  fresh-root default keeps the original v1 behavior bit-identical —
  re-searching from the root each move, the root-prior encoding the
  network's (fresher) knowledge, `wasted_slots` quantifying the
  orphan overhead. With reuse on, the node budget widens to
  `max_simulations + tree_reuse_budget + 1` slots and a batched
  root-promotion pass (`ops/subtree_reuse.py`) compacts the chosen
  child's subtree into the leading rows after each move; the next
  search merges those carried edge statistics under a *fresh* root
  evaluation (exact network value, re-applied Dirichlet noise) and
  inserts new waves at a per-game base — `CarriedTree` rides the
  caller's scan/session carry, so reuse costs zero extra dispatches.
"""

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from ..config.mcts_config import MCTSConfig
from ..env.engine import EnvState, TriangleEnv
from ..features.core import FeatureExtractor
from ..ops import backup_update, gather_rows, subtree_promote
from ..telemetry.device_stats import (
    DEPTH_BINS,
    beacon_every,
    device_stats_enabled,
    emit_beacon,
)


@struct.dataclass
class Tree:
    """Edge-indexed search arrays, batched over B games."""

    node_state: EnvState  # (B, N, ...) game state at each node slot
    e_visits: jax.Array  # (B, N, A) f32 edge visit counts
    e_value: jax.Array  # (B, N, A) f32 sum of discounted returns G(edge)
    e_reward: jax.Array  # (B, N, A) f32 reward on the edge (set at expand)
    children: jax.Array  # (B, N, A) f32 child slot id; -1 = unexpanded
    prior: jax.Array  # (B, N, A) f32 masked policy priors
    valid: jax.Array  # (B, N, A) f32 1.0 where the action is valid
    terminal: jax.Array  # (B, N) bool
    root_value0: jax.Array  # (B,) f32 network value of the root at init


@struct.dataclass
class CarriedTree:
    """A promoted search tree carried across moves (subtree reuse).

    `tree` holds the chosen child's subtree compacted into the leading
    rows (BFS order, freed rows zeroed) by `BatchedMCTS.promote`;
    `valid[b]` gates the merge (False = next search starts fresh:
    unexpanded chosen child, episode reset, weight reload, serve lane
    churn); `base[b]` = retained row count = the next search's
    insertion base. Rides the caller's carry (rollout scan, megastep
    program, serve lane state) so reuse never adds a dispatch.
    """

    tree: Tree
    valid: jax.Array  # (B,) bool
    base: jax.Array  # (B,) int32


@struct.dataclass
class SearchOutput:
    """Result of one batched search."""

    visit_counts: jax.Array  # (B, A) float32 root child visit counts
    root_value: jax.Array  # (B,) float32 mean backed-up root value
    root_prior: jax.Array  # (B, A) float32 noisy root prior (debug)
    total_simulations: jax.Array  # () int32
    wasted_slots: jax.Array  # (B,) int32 orphan node slots (see module doc)
    # Gumbel root search outputs (mcts/gumbel.py). PUCT fills
    # sentinels so both search kinds share one pytree structure (the
    # playout-cap lax.cond needs matching branches):
    # selected_action -1 = "select from visit counts on the host path";
    # improved_policy zeros = "build the target from visit counts".
    selected_action: jax.Array  # (B,) int32
    improved_policy: jax.Array  # (B, A) float32
    # Device telemetry stat-pack (telemetry/device_stats.py): a small
    # dict of fixed-shape f32 search-health statistics (leaf-depth
    # histogram, root-visit entropy/concentration, max |value|, slot
    # occupancy, reuse retained-fraction), or None when
    # TelemetryConfig.DEVICE_STATS is off — it rides the caller's
    # existing fetch, costing zero extra dispatches. Both search kinds
    # (PUCT and Gumbel) produce the same structure so the playout-cap
    # lax.cond branches keep matching pytrees.
    stats: Any = None


class BatchedMCTS:
    """PUCT search bound to (env, features, model); `search` is jitted.

    `evaluate` contract: the Flax model applied to extracted features,
    returning (policy_logits, value_logits -> scalar values) — the same
    role as the reference's `AlphaZeroNetworkInterface.evaluate_batch`
    (`alphatriangle/nn/network.py:242-318`) but traced into the search.
    """

    def __init__(
        self,
        env: TriangleEnv,
        extractor: FeatureExtractor,
        model: Any,
        config: MCTSConfig,
        value_support: jax.Array,
    ):
        self.env = env
        self.extractor = extractor
        self.model = model
        self.config = config
        self.support = value_support
        # Subtree reuse widens the node budget: up to `reuse_slots`
        # retained rows (promoted subtree incl. its root) plus a full
        # search's worth of fresh insertions. Fresh-root (the default)
        # keeps the original max_simulations + 1 exactly.
        if config.tree_reuse:
            budget = config.tree_reuse_budget or config.max_simulations
            self.reuse_slots = budget + 1
        else:
            self.reuse_slots = 1
        self.num_nodes = config.max_simulations + self.reuse_slots
        self.action_dim = env.action_dim
        # Wave size: largest divisor of max_simulations <= mcts_batch_size,
        # so waves tile the simulation budget exactly.
        w = max(1, min(config.mcts_batch_size, config.max_simulations))
        while config.max_simulations % w:
            w -= 1
        self.wave_size = w
        self.num_waves = config.max_simulations // w
        # Snapshot of the device-stats flag at construction: it shapes
        # the compiled programs (SearchOutput.stats leaf), so engines
        # fold it into their AOT cache extras and never flip it on a
        # live instance.
        self.device_stats = device_stats_enabled()
        self.search = jax.jit(self._search)

    # --- network evaluation ----------------------------------------------

    def _evaluate(self, variables, states: EnvState):
        """Batched leaf eval: states (B-leading) -> (priors (B,A), values (B,)).

        Priors are masked to valid actions and renormalized (uniform over
        valid when the network mass on valid actions vanishes — the
        reference's fallback, `nn/network.py:200-215`).
        """
        from ..nn.precision import dequantize_params

        grids, others = jax.vmap(self.extractor.extract)(states)
        # Int8 weight-only inference (nn/precision.py): marker-dict
        # leaves dequantize to bf16 here, at the one place every search
        # family evaluates the net; unquantized trees pass through.
        policy_logits, value_logits = self.model.apply(
            dequantize_params(variables), grids, others, train=False
        )
        valid = jax.vmap(self.env.valid_action_mask)(states)  # (B, A)
        masked_logits = jnp.where(valid, policy_logits, -jnp.inf)
        # Softmax over valid actions only; all-invalid rows -> zeros.
        any_valid = valid.any(axis=-1, keepdims=True)
        safe_logits = jnp.where(any_valid, masked_logits, 0.0)
        priors = jax.nn.softmax(safe_logits, axis=-1)
        priors = jnp.where(valid, priors, 0.0)
        norm = priors.sum(axis=-1, keepdims=True)
        uniform = valid.astype(jnp.float32) / jnp.maximum(
            valid.sum(axis=-1, keepdims=True), 1
        )
        priors = jnp.where(norm > 1e-9, priors / jnp.maximum(norm, 1e-9), uniform)
        value_probs = jax.nn.softmax(value_logits, axis=-1)
        values = jnp.sum(value_probs * self.support, axis=-1)
        return priors, values, valid

    # --- the search -------------------------------------------------------

    def _init_tree(self, variables, root_states: EnvState, rng) -> Tree:
        """Batched tree init: root eval + Dirichlet noise."""
        cfg = self.config
        batch = root_states.done.shape[0]
        n, a = self.num_nodes, self.action_dim

        priors, values, valid = self._evaluate(variables, root_states)
        root_terminal = root_states.done
        root_value = jnp.where(root_terminal, 0.0, values)

        # Dirichlet root noise over valid actions (eps=0 or alpha=0 -> off).
        if cfg.dirichlet_epsilon > 0 and cfg.dirichlet_alpha > 0:
            gammas = jax.random.gamma(
                rng, cfg.dirichlet_alpha, shape=(batch, a)
            )
            gammas = jnp.where(valid, gammas, 0.0)
            noise = gammas / jnp.maximum(
                gammas.sum(axis=-1, keepdims=True), 1e-9
            )
            priors = (1.0 - cfg.dirichlet_epsilon) * priors + (
                cfg.dirichlet_epsilon
            ) * noise
            priors = jnp.where(valid, priors, 0.0)

        def broadcast_to_nodes(x):
            """Tile each game's root state across its N node slots."""
            return jnp.broadcast_to(x[:, None], (batch, n) + x.shape[1:])

        node_state = jax.tree_util.tree_map(broadcast_to_nodes, root_states)
        zeros_na = jnp.zeros((batch, n, a), dtype=jnp.float32)
        return Tree(
            node_state=node_state,
            e_visits=zeros_na,
            e_value=zeros_na,
            e_reward=zeros_na,
            children=jnp.full((batch, n, a), -1.0, dtype=jnp.float32),
            prior=zeros_na.at[:, 0].set(priors),
            valid=zeros_na.at[:, 0].set(valid.astype(jnp.float32)),
            terminal=jnp.zeros((batch, n), dtype=bool).at[:, 0].set(root_terminal),
            root_value0=root_value,
        )

    def _descend_wave(
        self,
        tree: Tree,
        wave_rng: jax.Array,
        batch: int,
        root_action: jax.Array | None = None,
    ):
        """W parallel recorded descents per tree.

        Returns a dict of (B, W[, D]) arrays: final (parent, action,
        existing child), and the recorded path (nodes, actions,
        traversal rewards, active mask) for backup. Gumbel score noise
        (`wave_noise_scale`) is sampled per level from `wave_rng` so
        no (B, W, D, A) tensor is ever materialized.

        `root_action` (B, W) int32, when given, forces each member's
        depth-0 action (the Gumbel sequential-halving allocation,
        mcts/gumbel.py); -1 entries are unforced (ordinary PUCT), and
        deeper levels always select by PUCT.
        """
        cfg = self.config
        w, a = self.wave_size, self.action_dim
        depth = cfg.max_depth

        # Per-wave dense stat block: one (B, N, 6A) tensor so each
        # descent level is a single batched matmul row-read.
        stats = jnp.concatenate(
            [
                tree.e_visits,
                tree.e_value,
                tree.e_reward,
                tree.prior,
                tree.valid,
                tree.children,
            ],
            axis=-1,
        )  # (B, N, 6A)

        def level(d, carry):
            node, action, stop, rec_node, rec_action, rec_reward, rec_active = carry
            # (B, W, 6A) exact row select; lowering per config (one-hot
            # MXU matmul / Pallas VMEM copy / XLA gather).
            rows = gather_rows(stats, node, mode=cfg.descent_gather)
            visits_r = rows[..., 0 * a : 1 * a]
            value_r = rows[..., 1 * a : 2 * a]
            reward_r = rows[..., 2 * a : 3 * a]
            prior_r = rows[..., 3 * a : 4 * a]
            valid_r = rows[..., 4 * a : 5 * a]
            child_r = rows[..., 5 * a : 6 * a]

            n_node = 1.0 + visits_r.sum(axis=-1, keepdims=True)
            q = jnp.where(
                visits_r > 0, value_r / jnp.maximum(visits_r, 1e-9), 0.0
            )
            u = (
                cfg.cpuct
                * prior_r
                * jnp.sqrt(n_node)
                / (1.0 + visits_r)
            )
            # Noise only matters with >1 wave member; at W=1 keep exact
            # PUCT so sequential configs reproduce reference selection.
            if w > 1 and cfg.wave_noise_scale > 0:
                noise = cfg.wave_noise_scale * jax.random.gumbel(
                    jax.random.fold_in(wave_rng, d), (batch, w, a)
                )
            else:
                noise = 0.0
            scores = jnp.where(valid_r > 0, q + u, -jnp.inf) + noise
            act = jnp.argmax(scores, axis=-1).astype(jnp.int32)  # (B, W)
            if root_action is not None:
                # -1 releases a member to ordinary PUCT selection.
                act = jnp.where((d == 0) & (root_action >= 0), root_action, act)
            act_oh = jax.nn.one_hot(act, a, dtype=jnp.float32)
            child = (
                (child_r * act_oh).sum(axis=-1).astype(jnp.int32)
            )  # (B, W); -1 = unexpanded
            r_edge = (reward_r * act_oh).sum(axis=-1)
            term = jnp.take_along_axis(tree.terminal, node, axis=1)
            stop_now = (child < 0) | (d + 1 >= depth) | term

            active = ~stop
            rec_node = rec_node.at[:, :, d].set(jnp.where(active, node, -1))
            rec_action = rec_action.at[:, :, d].set(
                jnp.where(active, act, -1)
            )
            rec_reward = rec_reward.at[:, :, d].set(
                jnp.where(active, r_edge, 0.0)
            )
            rec_active = rec_active.at[:, :, d].set(active)

            action = jnp.where(stop, action, act)
            node = jnp.where(stop | stop_now, node, child)
            return (
                node,
                action,
                stop | stop_now,
                rec_node,
                rec_action,
                rec_reward,
                rec_active,
            )

        node0 = jnp.zeros((batch, w), jnp.int32)
        carry = (
            node0,
            jnp.zeros((batch, w), jnp.int32),
            jnp.zeros((batch, w), bool),
            jnp.full((batch, w, depth), -1, jnp.int32),
            jnp.full((batch, w, depth), -1, jnp.int32),
            jnp.zeros((batch, w, depth), jnp.float32),
            jnp.zeros((batch, w, depth), bool),
        )
        parents, actions, _, rec_node, rec_action, rec_reward, rec_active = (
            jax.lax.fori_loop(0, depth, level, carry, unroll=True)
        )
        existing = (
            jnp.take_along_axis(
                tree.children.reshape(batch, -1),
                (parents * a + actions),
                axis=1,
            )
        ).astype(jnp.int32)  # (B, W)
        return {
            "parents": parents,
            "actions": actions,
            "existing": existing,
            "rec_node": rec_node,
            "rec_action": rec_action,
            "rec_reward": rec_reward,
            "rec_active": rec_active,
        }

    def _wave(self, variables, batch: int, carry, wave_rng, root_action=None):
        """One wave: W parallel sims across all B trees.

        `carry` is `(tree, wasted, base)` plus — when `device_stats` is
        on — a trailing `(DEPTH_BINS,) f32` leaf-depth histogram the
        wave accumulates into; the return matches the input arity.
        """
        cfg = self.config
        tree, wasted, base = carry[:3]
        hist = carry[3] if self.device_stats and len(carry) > 3 else None
        w, a = self.wave_size, self.action_dim
        depth = cfg.max_depth
        barange = jnp.arange(batch)
        warange = jnp.arange(w)
        bcol = barange[:, None]

        # 1. W parallel recorded descents per tree.
        d = self._descend_wave(tree, wave_rng, batch, root_action)
        parents, actions, existing = d["parents"], d["actions"], d["existing"]
        is_new = existing < 0

        # Canonicalize within-wave duplicates: members that chose the
        # same edge share one child node — the one belonging to the
        # highest member index (matching the `.max()` scatter below).
        key = parents * a + actions  # (B, W)
        same = key[:, :, None] == key[:, None, :]  # (B, W, W)
        later = warange[None, None, :] > warange[None, :, None]
        is_canon = ~(same & later).any(axis=-1)  # (B, W)

        # 2. Expansion: one batched env.step over all B*W edges.
        # (The engine is deterministic given the node's PRNG state, so
        # duplicate/revisited edges reproduce the same child state.)
        parent_states = jax.tree_util.tree_map(
            lambda x: x[bcol, parents].reshape((batch * w,) + x.shape[2:]),
            tree.node_state,
        )
        new_states, rewards, dones = jax.vmap(self.env.step)(
            parent_states, actions.reshape(-1)
        )
        rewards = rewards.reshape(batch, w)
        dones = dones.reshape(batch, w)

        # 3. Evaluation: ONE fused network call for all B*W leaves.
        priors, values, valid = self._evaluate(variables, new_states)
        leaf_values = jnp.where(dones, 0.0, values.reshape(batch, w))

        # 4. Insert the wave's W node slots as one block at [base, base+W).
        if jnp.ndim(base) == 0:
            # Shared scalar base (fresh-root search): a dynamic-slice
            # block write, the original lowering verbatim.
            def insert(buf, block):
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, block.astype(buf.dtype), base, axis=1
                )

            slot_ids = (base + warange[None, :]).astype(jnp.float32)  # (1, W)
        else:
            # Per-game base (subtree reuse: each game retained a
            # different row count): scatter rows [base_b, base_b + W).
            slots = base[:, None] + warange[None, :]  # (B, W)

            def insert(buf, block):
                return buf.at[bcol, slots].set(block.astype(buf.dtype))

            slot_ids = slots.astype(jnp.float32)

        ns = jax.tree_util.tree_map(
            lambda buf, x: insert(buf, x.reshape((batch, w) + x.shape[1:])),
            tree.node_state,
            new_states,
        )
        live = is_new & is_canon
        tree = tree.replace(
            node_state=ns,
            prior=insert(tree.prior, priors.reshape(batch, w, a)),
            valid=insert(
                tree.valid, valid.reshape(batch, w, a).astype(jnp.float32)
            ),
            terminal=insert(tree.terminal, dones),
        )

        # 5. Insertion + backup along the recorded paths as one fused
        # edge-plane update (ops/mcts_backup.py; lowering per config).
        # Suffix returns first: G_d = r_d + discount * G_{d+1}, where
        # the deepest active level's reward is the fresh step reward (a
        # new edge has no stored reward yet; for revisits the stored
        # value is identical by determinism).
        rec_node, rec_action = d["rec_node"], d["rec_action"]
        rec_active = d["rec_active"]  # (B, W, D)
        last_idx = rec_active.sum(axis=-1) - 1  # (B, W) deepest level
        if hist is not None:
            # Leaf-depth histogram: one count per simulation at its
            # descent depth (terminal-root sims land in bin 0; depths
            # past the last bin clip into it). A (B*W, BINS) one-hot
            # sum — vector math on data already in registers.
            d_bin = jnp.clip(last_idx, 0, DEPTH_BINS - 1).reshape(-1)
            hist = hist + jax.nn.one_hot(
                d_bin, DEPTH_BINS, dtype=jnp.float32
            ).sum(axis=0)
        g = leaf_values  # (B, W)
        contrib = []
        for lvl in range(depth - 1, -1, -1):
            is_last = rec_active[:, :, lvl] & (last_idx == lvl)
            r_lvl = jnp.where(
                is_last, rewards, d["rec_reward"][:, :, lvl]
            )
            g = jnp.where(
                rec_active[:, :, lvl], r_lvl + cfg.discount * g, g
            )
            contrib.append(g)
        contrib.reverse()  # contrib[lvl] = G at level lvl, (B, W)

        e_visits, e_value, children, e_reward = backup_update(
            tree.e_visits,
            tree.e_value,
            tree.children,
            tree.e_reward,
            parents,
            actions,
            jnp.where(is_new, slot_ids, -1.0),
            rewards,
            rec_node,
            rec_action,
            rec_active,
            jnp.stack(contrib, axis=-1),
            mode=cfg.backup_update,
        )
        tree = tree.replace(
            e_visits=e_visits,
            e_value=e_value,
            children=children,
            e_reward=e_reward,
        )

        wasted = wasted + (w - live.sum(axis=1, dtype=jnp.int32))
        if hist is not None:
            return tree, wasted, base + w, hist
        return tree, wasted, base + w

    def _stats_seed(self) -> tuple:
        """The extra carry tail `_wave` accumulates when device stats
        are on: a zeroed leaf-depth histogram. Empty tuple when off, so
        unchanged configs carry exactly the original 3-tuple."""
        if not self.device_stats:
            return ()
        return (jnp.zeros((DEPTH_BINS,), jnp.float32),)

    def _run_waves(self, variables, batch: int, tree: Tree, wave_rng, base0):
        """`num_waves` waves from `tree`; `base0` is the first insertion
        base — scalar 1 (fresh root) or a per-game (B,) vector (reuse).
        Returns `(tree, wasted, base)` plus the depth histogram when
        device stats are on (`_stats_seed`)."""

        def wave_body(k, carry):
            emit_beacon("search_wave", k, every=beacon_every())
            return self._wave(
                variables,
                batch,
                carry,
                jax.random.fold_in(wave_rng, k),
            )

        return jax.lax.fori_loop(
            0,
            self.num_waves,
            wave_body,
            (tree, jnp.zeros((batch,), jnp.int32), base0)
            + self._stats_seed(),
        )

    def _stat_pack(
        self,
        tree: Tree,
        wasted: jax.Array,
        final_base,
        hist: jax.Array,
        batch: int,
        reused: jax.Array | None = None,
    ) -> dict:
        """KataGo-style search-health statistics (arXiv:1902.10565)
        from arrays already on device — a handful of (B, A)-sized
        reductions appended to the program, returned through the
        caller's existing fetch.

        All leaves are fixed-shape f32 scalars except `depth_hist`
        ((DEPTH_BINS,)); the structure is identical across search kinds
        and reuse modes so downstream pytrees always match."""
        visits = tree.e_visits[:, 0, :]  # (B, A) root edge visits
        total = visits.sum(axis=-1)  # (B,)
        p = visits / jnp.maximum(total[:, None], 1.0)
        entropy = -jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-12)), 0.0).sum(
            axis=-1
        )
        # Mean |Q| excursion over visited root edges, and the root
        # value itself: a diverging value head shows up here waves
        # before it poisons the iteration-mean loss metrics.
        q_abs = jnp.where(
            visits > 0,
            jnp.abs(tree.e_value[:, 0, :]) / jnp.maximum(visits, 1e-9),
            0.0,
        )
        value_abs_max = jnp.maximum(
            q_abs.max(), jnp.abs(tree.root_value0).max()
        )
        live = (
            jnp.broadcast_to(
                jnp.asarray(final_base, jnp.float32), (batch,)
            )
            - wasted.astype(jnp.float32)
        )
        if reused is None:
            reuse_frac = jnp.float32(0.0)
        else:
            reuse_frac = (reused / jnp.maximum(total, 1.0)).mean()
        return {
            "depth_hist": hist,
            "root_entropy": entropy.mean(),
            "root_concentration": p.max(axis=-1).mean(),
            "value_abs_max": value_abs_max,
            "occupancy": (live / float(self.num_nodes)).mean(),
            "reuse_frac": reuse_frac,
        }

    def _output_from_tree(
        self, tree: Tree, wasted: jax.Array, batch: int
    ) -> SearchOutput:
        """Root stats are just row 0 of the edge planes."""
        cfg = self.config
        visit_counts = tree.e_visits[:, 0, :]
        root_visits = 1.0 + visit_counts.sum(axis=-1)
        root_value = (
            tree.root_value0 + tree.e_value[:, 0, :].sum(axis=-1)
        ) / root_visits
        return SearchOutput(
            visit_counts=visit_counts,
            root_value=root_value,
            root_prior=tree.prior[:, 0],
            total_simulations=jnp.int32(cfg.max_simulations * batch),
            wasted_slots=wasted,
            selected_action=jnp.full((batch,), -1, jnp.int32),
            improved_policy=jnp.zeros_like(visit_counts),
        )

    def _search(
        self, variables, root_states: EnvState, rng: jax.Array
    ) -> SearchOutput:
        """Run `max_simulations` batched simulations from `root_states`."""
        batch = root_states.done.shape[0]
        rng, noise_rng, wave_rng = jax.random.split(rng, 3)
        tree = self._init_tree(variables, root_states, noise_rng)
        tree, wasted, base, *rest = self._run_waves(
            variables, batch, tree, wave_rng, jnp.int32(1)
        )
        out = self._output_from_tree(tree, wasted, batch)
        if self.device_stats:
            out = out.replace(
                stats=self._stat_pack(tree, wasted, base, rest[0], batch)
            )
        return out

    # --- subtree reuse (MCTSConfig.tree_reuse; ops/subtree_reuse.py) ---

    def _search_carried(
        self,
        variables,
        root_states: EnvState,
        rng: jax.Array,
        carried: CarriedTree,
    ) -> tuple[SearchOutput, Tree, jax.Array]:
        """`_search` seeded with a promoted tree where `carried.valid`.

        The root row is ALWAYS re-taken from a fresh root evaluation —
        exact network value (`root_value0`), fresh masked priors with
        Dirichlet noise re-applied, current-state validity/terminal —
        so reuse carries only *edge statistics* (visits, returns,
        rewards, child links) plus interior priors/states. Lanes with
        `valid=False` reproduce the fresh-root search exactly. Returns
        `(output, final_tree, reused)` where `reused[b]` counts the
        root visits inherited from the carry (the leaf evaluations this
        move did not have to spend).
        """
        batch = root_states.done.shape[0]
        rng, noise_rng, wave_rng = jax.random.split(rng, 3)
        fresh = self._init_tree(variables, root_states, noise_rng)
        ct = carried.tree
        ok = carried.valid  # (B,)
        okr = ok[:, None, None]

        def merge(c_plane, f_plane):
            return jnp.where(okr, c_plane, f_plane)

        def merge_state(c, f):
            okx = ok.reshape((batch,) + (1,) * (c.ndim - 1))
            m = jnp.where(okx, c, f)
            # Row 0 always holds the exact current root state (the
            # promoted row 0 equals it by env determinism; this pins it
            # structurally rather than by argument).
            return m.at[:, 0].set(f[:, 0])

        tree = Tree(
            node_state=jax.tree_util.tree_map(
                merge_state, ct.node_state, fresh.node_state
            ),
            e_visits=merge(ct.e_visits, fresh.e_visits),
            e_value=merge(ct.e_value, fresh.e_value),
            e_reward=merge(ct.e_reward, fresh.e_reward),
            children=merge(ct.children, fresh.children),
            prior=merge(ct.prior.at[:, 0].set(fresh.prior[:, 0]), fresh.prior),
            valid=merge(ct.valid.at[:, 0].set(fresh.valid[:, 0]), fresh.valid),
            terminal=jnp.where(
                ok[:, None],
                ct.terminal.at[:, 0].set(fresh.terminal[:, 0]),
                fresh.terminal,
            ),
            root_value0=fresh.root_value0,
        )
        reused = jnp.where(ok, ct.e_visits[:, 0, :].sum(axis=-1), 0.0)
        base0 = jnp.where(ok, jnp.maximum(carried.base, 1), 1).astype(
            jnp.int32
        )
        tree, wasted, base, *rest = self._run_waves(
            variables, batch, tree, wave_rng, base0
        )
        out = self._output_from_tree(tree, wasted, batch)
        if self.device_stats:
            out = out.replace(
                stats=self._stat_pack(
                    tree, wasted, base, rest[0], batch, reused=reused
                )
            )
        return out, tree, reused

    def promote(self, tree: Tree, actions: jax.Array) -> CarriedTree:
        """Batched root promotion: compact each game's chosen child's
        subtree into the leading rows (ops/subtree_reuse.py; lowering
        per `tree_reuse_backend`). `valid` is False where the chosen
        child was never expanded; callers additionally clear lanes on
        episode reset / churn."""
        cfg = self.config
        (
            e_visits, e_value, e_reward, children, prior, valid,
            terminal, state_index, promo_valid, retained,
        ) = subtree_promote(
            tree.e_visits,
            tree.e_value,
            tree.e_reward,
            tree.children,
            tree.prior,
            tree.valid,
            tree.terminal,
            actions.astype(jnp.int32),
            max_retained=self.reuse_slots,
            bfs_rounds=cfg.max_depth,
            mode=cfg.tree_reuse_backend,
        )
        batch = actions.shape[0]
        bcol = jnp.arange(batch)[:, None]
        node_state = jax.tree_util.tree_map(
            lambda x: x[bcol, state_index], tree.node_state
        )
        promoted = Tree(
            node_state=node_state,
            e_visits=e_visits,
            e_value=e_value,
            e_reward=e_reward,
            children=children,
            prior=prior,
            valid=valid,
            terminal=terminal,
            # Overwritten by the fresh root evaluation on the next
            # `_search_carried`; zero keeps the carry deterministic.
            root_value0=jnp.zeros_like(tree.root_value0),
        )
        return CarriedTree(
            tree=promoted,
            valid=promo_valid,
            base=jnp.maximum(retained, 1),
        )

    def zero_carried(self, root_states: EnvState) -> CarriedTree:
        """An all-invalid carry with the right static shapes (scan /
        session-lane initialization; `root_states` only donates shapes)."""
        batch = root_states.done.shape[0]
        n, a = self.num_nodes, self.action_dim

        def broadcast_to_nodes(x):
            # .copy() forces a fresh buffer per leaf: the carry is
            # donated by the rollout chunk, and donating one aliased
            # buffer through two arguments is an XLA error.
            return jnp.broadcast_to(x[:, None], (batch, n) + x.shape[1:]).copy()

        def zeros_na():
            return jnp.zeros((batch, n, a), dtype=jnp.float32)

        return CarriedTree(
            tree=Tree(
                node_state=jax.tree_util.tree_map(
                    broadcast_to_nodes, root_states
                ),
                e_visits=zeros_na(),
                e_value=zeros_na(),
                e_reward=zeros_na(),
                children=jnp.full((batch, n, a), -1.0, dtype=jnp.float32),
                prior=zeros_na(),
                valid=zeros_na(),
                terminal=jnp.zeros((batch, n), dtype=bool),
                root_value0=jnp.zeros((batch,), dtype=jnp.float32),
            ),
            valid=jnp.zeros((batch,), dtype=bool),
            base=jnp.ones((batch,), dtype=jnp.int32),
        )
