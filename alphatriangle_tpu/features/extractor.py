"""Host-side parity surface: `extract_state_features(game_state, model_config)`.

Mirrors the reference entry point
(`alphatriangle/features/extractor.py:150-171`) but delegates to the
same jitted jnp pipeline the device self-play path uses
(`features.core.FeatureExtractor`), so host and device features agree
by construction. Includes the reference's finiteness scrub.
"""

import logging

import numpy as np

from ..config.model_config import ModelConfig
from ..env.game_state import GameState
from ..utils.types import StateType
from .core import get_feature_extractor

logger = logging.getLogger(__name__)


def extract_state_features(
    game_state: GameState, model_config: ModelConfig
) -> StateType:
    """GameState -> {grid (C,H,W), other_features (F,)} float32 NumPy."""
    fe = get_feature_extractor(game_state._env, model_config)
    grid, other = fe.extract_1(game_state._state)
    grid_np = np.asarray(grid, dtype=np.float32)
    other_np = np.asarray(other, dtype=np.float32)
    if not np.all(np.isfinite(other_np)):
        logger.error("Non-finite values in other_features; scrubbing to 0.")
        other_np = np.nan_to_num(other_np, nan=0.0, posinf=0.0, neginf=0.0)
    if not np.all(np.isfinite(grid_np)):
        logger.error("Non-finite values in grid features; scrubbing to 0.")
        grid_np = np.nan_to_num(grid_np, nan=0.0, posinf=0.0, neginf=0.0)
    return {"grid": grid_np, "other_features": other_np}
