"""Feature extraction: game state -> NN inputs.

Reference surface: `alphatriangle/features/` (extractor + Numba grid
kernels). Here the whole pipeline is vectorized jnp (`core`), with the
host parity entry point in `extractor` and the scalar grid reductions in
`grid_features`.
"""

from .core import FeatureExtractor, build_shape_feature_table, get_feature_extractor
from .extractor import extract_state_features
from .grid_features import bumpiness, column_heights, count_holes

__all__ = [
    "FeatureExtractor",
    "build_shape_feature_table",
    "bumpiness",
    "column_heights",
    "count_holes",
    "extract_state_features",
    "get_feature_extractor",
]
