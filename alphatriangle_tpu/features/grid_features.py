"""Grid scalar features: column heights, holes, bumpiness.

The reference computes these with Numba ``@njit`` scalar loops
(`alphatriangle/features/grid_features.py:7-42`). On TPU they are plain
vectorized reductions that XLA fuses into the surrounding feature
extraction — no custom kernel needed.

Semantics (behavior contract, matching the reference exactly):
- ``height[c]`` = (index of the lowest occupied playable row in column
  c) + 1, i.e. ``max_r + 1`` scanning rows top-to-bottom; 0 if empty.
- ``holes`` = number of empty playable cells at rows above the height
  mark, i.e. with ``r < height[c]``.
- ``bumpiness`` = sum of |height[c] - height[c+1]| over adjacent columns.
"""

import jax.numpy as jnp
import numpy as np
from jax import Array


def column_heights(occupied: Array, death: Array) -> Array:
    """(C,) int32 column heights from (R, C) occupancy/death masks."""
    rows = occupied.shape[0]
    playable_occ = occupied & ~death
    row_idx = jnp.arange(1, rows + 1, dtype=jnp.int32)[:, None]  # (R, 1)
    return jnp.max(jnp.where(playable_occ, row_idx, 0), axis=0)


def count_holes(occupied: Array, death: Array, heights: Array) -> Array:
    """() int32 count of empty playable cells below the height mark."""
    rows = occupied.shape[0]
    row_idx = jnp.arange(rows, dtype=jnp.int32)[:, None]  # (R, 1)
    below = row_idx < heights[None, :]
    return jnp.sum(below & ~occupied & ~death, dtype=jnp.int32)


def bumpiness(heights: Array) -> Array:
    """() float32 total absolute adjacent-column height difference."""
    return jnp.abs(jnp.diff(heights)).sum().astype(jnp.float32)


# --- NumPy twins (host-side parity checks / no-JAX consumers) -------------


def column_heights_np(occupied: np.ndarray, death: np.ndarray) -> np.ndarray:
    rows = occupied.shape[0]
    playable_occ = occupied & ~death
    row_idx = np.arange(1, rows + 1, dtype=np.int32)[:, None]
    return np.max(np.where(playable_occ, row_idx, 0), axis=0).astype(np.int32)


def count_holes_np(
    occupied: np.ndarray, death: np.ndarray, heights: np.ndarray
) -> int:
    rows = occupied.shape[0]
    row_idx = np.arange(rows, dtype=np.int32)[:, None]
    below = row_idx < heights[None, :]
    return int(np.sum(below & ~occupied & ~death))


def bumpiness_np(heights: np.ndarray) -> float:
    return float(np.abs(np.diff(heights)).sum())
