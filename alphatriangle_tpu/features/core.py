"""Batched state -> NN-input feature extraction (pure jnp).

TPU-native redesign of the reference extractor
(`alphatriangle/features/extractor.py:33-147`): the same 30-dim layout,
but computed as vectorized array ops directly on the engine's
struct-of-arrays `EnvState`, vmappable across a whole batch of games so
self-play feature extraction is one fused XLA computation instead of a
per-state Python/Numba pass.

Feature layout (parity contract, verified by tests against
`expected_other_features_dim`):
- grid: (GRID_INPUT_CHANNELS, R, C) float32; channel 0 holds
  1.0 occupied-playable / 0.0 empty / -1.0 death (extractor.py:33-46).
- other_features, concatenated:
  * per-slot shape features, 7 each (extractor.py:48-85): triangle
    count / 5, up fraction, down fraction, bbox height / ROWS,
    effective width / COLS (width * 0.75 + 0.25 — triangles overlap
    horizontally), bbox row centroid / ROWS, bbox col centroid / COLS;
    all clipped to [0, 1], zeros for empty slots.
  * slot availability, NUM_SHAPE_SLOTS values (extractor.py:87-90).
  * 6 scalars (extractor.py:92-118): score / 100 clipped to [-5, 5],
    mean height / ROWS, max height / ROWS, holes / playable cells,
    bumpiness / (COLS-1) / ROWS, step / 1000 clipped to [0, 1].

Shape features depend only on the (static) shape bank, so they are
precomputed host-side into an (S+1, 7) table and the device pass is a
single gather by slot shape index.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from ..config.env_config import EnvConfig
from ..config.model_config import ModelConfig
from ..config.validation import (
    FEATURES_PER_SHAPE,
    expected_other_features_dim,
)
from ..env.engine import EnvState, TriangleEnv
from ..env.shapes import ShapeBank
from . import grid_features


def build_shape_feature_table(bank: ShapeBank, cfg: EnvConfig) -> np.ndarray:
    """(S + 1, 7) float32: row s = features of shape s; last row = zeros.

    The trailing zero row is the gather target for empty slots
    (shape_idx == -1), so the device pass needs no branch.
    """
    table = np.zeros((bank.n_shapes + 1, FEATURES_PER_SHAPE), dtype=np.float32)
    for s, cells in enumerate(bank.shapes):
        n = len(cells)
        ups = sum(1 for r, c in cells if (r + c) % 2 == 0)
        min_r = min(r for r, _ in cells)
        max_r = max(r for r, _ in cells)
        min_c = min(c for _, c in cells)
        max_c = max(c for _, c in cells)
        height = max_r - min_r + 1
        width_eff = (max_c - min_c + 1) * 0.75 + 0.25
        table[s] = (
            np.clip(n / 5.0, 0.0, 1.0),
            ups / n,
            (n - ups) / n,
            np.clip(height / cfg.ROWS, 0.0, 1.0),
            np.clip(width_eff / cfg.COLS, 0.0, 1.0),
            np.clip(((min_r + max_r) / 2.0) / cfg.ROWS, 0.0, 1.0),
            np.clip(((min_c + max_c) / 2.0) / cfg.COLS, 0.0, 1.0),
        )
    return table


class FeatureExtractor:
    """Static feature pipeline bound to one (EnvConfig, ModelConfig) pair.

    Like `TriangleEnv`, instances are immutable and hold only
    precomputed constants; `extract` / `extract_batch` are pure.
    """

    def __init__(self, env: TriangleEnv, model_config: ModelConfig):
        self.env = env
        self.model_config = model_config
        expected = expected_other_features_dim(env.cfg)
        if model_config.OTHER_NN_INPUT_FEATURES_DIM != expected:
            raise ValueError(
                f"ModelConfig.OTHER_NN_INPUT_FEATURES_DIM="
                f"{model_config.OTHER_NN_INPUT_FEATURES_DIM} does not match "
                f"the feature layout ({expected}) for this EnvConfig."
            )
        self.other_dim = expected
        self._shape_table = jnp.asarray(
            build_shape_feature_table(env.bank, env.cfg)
        )
        self._death = jnp.asarray(env.geometry.death)
        self._n_playable = max(int((~env.geometry.death).sum()), 1)
        self.extract_batch = jax.jit(jax.vmap(self.extract))
        self.extract_1 = jax.jit(self.extract)

    def extract(self, state: EnvState) -> tuple[Array, Array]:
        """One game's (grid, other_features); vmap for batches."""
        cfg = self.env.cfg
        death = self._death

        occupied = self.env.unpack_grid(state.occupied)  # (R, C) bool
        grid0 = jnp.where(
            death, jnp.float32(-1.0), occupied.astype(jnp.float32)
        )
        grid = jnp.zeros(
            (self.model_config.GRID_INPUT_CHANNELS, cfg.ROWS, cfg.COLS),
            dtype=jnp.float32,
        )
        grid = grid.at[0].set(grid0)

        # Shape features: gather from the static table; -1 -> zero row.
        slot_rows = jnp.where(
            state.shape_idx >= 0, state.shape_idx, self._shape_table.shape[0] - 1
        )
        shape_feats = self._shape_table[slot_rows].reshape(-1)  # (SLOTS*7,)
        availability = (state.shape_idx >= 0).astype(jnp.float32)  # (SLOTS,)

        heights = grid_features.column_heights(occupied, death)
        holes = grid_features.count_holes(occupied, death, heights)
        bump = grid_features.bumpiness(heights)
        rows_f = jnp.float32(cfg.ROWS)
        explicit = jnp.stack(
            [
                jnp.clip(state.score / 100.0, -5.0, 5.0),
                heights.mean(dtype=jnp.float32) / rows_f,
                heights.max().astype(jnp.float32) / rows_f,
                holes.astype(jnp.float32) / self._n_playable,
                (bump / max(cfg.COLS - 1, 1)) / rows_f,
                jnp.clip(state.step_count.astype(jnp.float32) / 1000.0, 0.0, 1.0),
            ]
        )
        other = jnp.concatenate([shape_feats, availability, explicit])
        return grid, other


# One extractor per (env-config, model-config) pair, mirroring the
# engine cache in env.game_state.
_EXTRACTOR_CACHE: dict[str, FeatureExtractor] = {}


def get_feature_extractor(
    env: TriangleEnv, model_config: ModelConfig
) -> FeatureExtractor:
    key = env.cfg.model_dump_json() + model_config.model_dump_json()
    fe = _EXTRACTOR_CACHE.get(key)
    if fe is None:
        fe = _EXTRACTOR_CACHE[key] = FeatureExtractor(env, model_config)
    return fe
